"""Bench-in-the-loop autotuner: the attribution plane closing its own loop.

PR 8 built the pricing side (``price_callable``: AOT cost analysis +
roofline verdicts, no allocation) and the measuring side (``StepClock``).
This module wires them into a two-stage sweep over kernel/knob configs:

1. **prune** — every candidate is priced with ``price_callable`` (an AOT
   compile of its train step from ``ShapeDtypeStruct``s); only the ``keep``
   best roofline estimates survive. Pricing a config costs one compile,
   never a training step, so the sweep can afford a wide grid.
2. **measure** — survivors run a handful of real steps under a
   ``StepClock``; the measured step time picks the winner. Rooflines rank,
   clocks decide.

The sweep is generic over knob dicts: the ResNet bench sweeps the fused
kernel set and batch bucket, the GPT bench sweeps ``remat``/``scan_blocks``
(and the FSDP ``gather_mode`` when the mesh has more than one device).
``bench.py`` records ``AutotuneResult.to_row()`` in its bench rows
(``autotune`` field), so a BENCH round documents the config that produced
it — reproducibility is the point.

``python -m kubeflow_tpu.training.autotune --quick`` runs the sweep on
toy shapes (CPU interpret-mode friendly); the ``autotune-smoke`` presubmit
keeps that path from rotting.
"""

from __future__ import annotations

import argparse
import json
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

#: knob dict -> roofline seconds (may raise; errors are recorded, not fatal)
PriceFn = Callable[[Dict[str, Any]], float]
#: knob dict -> measured seconds per step (may raise)
MeasureFn = Callable[[Dict[str, Any]], float]


@dataclass
class TunedCandidate:
    """One swept config: knobs + what the two stages said about it."""

    knobs: Dict[str, Any]
    est_seconds: Optional[float] = None       # stage-1 roofline price
    measured_seconds: Optional[float] = None  # stage-2 StepClock pick
    pruned: bool = False                      # dropped after pricing
    error: Optional[str] = None               # a stage raised; excluded

    def to_dict(self) -> Dict[str, Any]:
        return {
            "knobs": self.knobs,
            "est_seconds": self.est_seconds,
            "measured_seconds": self.measured_seconds,
            "pruned": self.pruned,
            "error": self.error,
        }


@dataclass
class AutotuneResult:
    """The sweep's verdict + full audit table."""

    family: str                       # "resnet" | "gpt" | ...
    chosen: Dict[str, Any]
    candidates: List[TunedCandidate] = field(default_factory=list)
    quick: bool = False

    def to_row(self) -> Dict[str, Any]:
        """Compact form for a bench row's ``autotune`` field."""
        measured = [c for c in self.candidates if c.measured_seconds is not None]
        return {
            "family": self.family,
            "chosen": self.chosen,
            "swept": len(self.candidates),
            "pruned": sum(1 for c in self.candidates if c.pruned),
            "measured": len(measured),
            "errors": sum(1 for c in self.candidates if c.error),
            "quick": self.quick,
        }

    def to_dict(self) -> Dict[str, Any]:
        d = self.to_row()
        d["candidates"] = [c.to_dict() for c in self.candidates]
        return d

    def render(self) -> str:
        lines = [f"# autotune[{self.family}] chosen: {self.chosen}"]
        for c in self.candidates:
            est = f"{c.est_seconds * 1e3:.3f}ms" if c.est_seconds is not None else "-"
            meas = (f"{c.measured_seconds * 1e3:.3f}ms"
                    if c.measured_seconds is not None else "-")
            tag = "PRUNED" if c.pruned else ("ERROR " + c.error if c.error else "")
            lines.append(f"  {c.knobs}  est={est}  measured={meas}  {tag}")
        return "\n".join(lines)


def sweep(
    family: str,
    candidates: List[Dict[str, Any]],
    *,
    measure: MeasureFn,
    price: Optional[PriceFn] = None,
    keep: int = 2,
    quick: bool = False,
    log: Optional[Callable[[str], None]] = None,
) -> AutotuneResult:
    """Run the two-stage sweep. With a ``price`` fn, only the ``keep``
    cheapest roofline estimates are measured; without one, every candidate
    is. The winner is the smallest measured step; if every measurement
    fails, the best (un-errored) estimate; if even pricing failed
    everywhere, the first candidate (the caller's default ordering)."""
    if not candidates:
        raise ValueError("sweep needs at least one candidate")
    say = log or (lambda s: None)
    table = [TunedCandidate(knobs=dict(k)) for k in candidates]

    if price is not None:
        for c in table:
            try:
                c.est_seconds = float(price(c.knobs))
            except Exception as exc:
                # pricing is advisory, never fatal — an unpriceable
                # candidate (e.g. collectives, invisible to single-program
                # cost analysis) is still MEASURED, just never pruned-by-
                # price and never eligible for the price fallback
                c.error = f"price: {exc}"
        priced = sorted((c for c in table if c.est_seconds is not None),
                        key=lambda c: c.est_seconds)
        for c in priced[max(1, keep):]:
            c.pruned = True
        say(f"autotune[{family}]: priced {len(priced)}/{len(table)}, "
            f"measuring {sum(1 for c in table if not c.pruned)}")

    for c in table:
        if c.pruned:
            continue
        try:
            start = time.perf_counter()
            c.measured_seconds = float(measure(c.knobs))
            say(f"autotune[{family}]: {c.knobs} -> "
                f"{c.measured_seconds * 1e3:.3f} ms/step "
                f"(swept in {time.perf_counter() - start:.1f}s)")
        except Exception as exc:
            c.error = (f"{c.error}; measure: {exc}" if c.error
                       else f"measure: {exc}")
            say(f"autotune[{family}]: {c.knobs} failed: {exc}")

    measured = [c for c in table if c.measured_seconds is not None]
    if measured:
        chosen = min(measured, key=lambda c: c.measured_seconds).knobs
    else:
        # no measurement survived anywhere (e.g. no hardware): the best
        # roofline estimate decides; with no estimates either, the first
        # candidate (the caller's default ordering) wins
        priced_ok = [c for c in table if c.est_seconds is not None]
        chosen = (min(priced_ok, key=lambda c: c.est_seconds).knobs
                  if priced_ok else table[0].knobs)
    return AutotuneResult(family=family, chosen=chosen, candidates=table,
                          quick=quick)


def measure_steps(compiled: Callable[[], Any], steps: int = 3) -> float:
    """Median wall-clock of ``steps`` calls to a zero-arg thunk that runs
    one step and blocks until the result is ready (StepClock's compute
    phase, without needing the full loop scaffolding)."""
    from kubeflow_tpu.tpu.profiling import StepClock

    clock = StepClock()
    for _ in range(steps):
        with clock.phase("compute"):
            compiled()
        clock.end_step()
    times = sorted(s.get("compute", 0.0) for s in clock.steps)
    return times[len(times) // 2]


# -- quick mode: toy shapes, CPU interpret-mode friendly ----------------------


def resnet_quick_candidates() -> List[Dict[str, Any]]:
    return [{"fused_blocks": False}, {"fused_blocks": True}]


def autotune_resnet_quick(steps: int = 2) -> AutotuneResult:
    """The ResNet sweep at toy shape: fused kernel set on/off, priced via
    the unfused reference (XLA credits no FLOPs in a Pallas call — same
    ground rule as bench.py's MFU numerator), measured with real grad
    steps on whatever backend is present."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models.resnet import BottleneckBlock, ResNet
    from kubeflow_tpu.training.attribution import price_callable

    batch, image = 4, 32
    x = jnp.zeros((batch, image, image, 3), jnp.float32)

    def build(fused: bool):
        return ResNet(stage_sizes=[1, 1], block_cls=BottleneckBlock,
                      num_classes=10, num_filters=8, fused_blocks=fused)

    ref = build(False)
    variables = ref.init(jax.random.PRNGKey(0), x, train=False)

    def price(knobs: Dict[str, Any]) -> float:
        struct_v = jax.eval_shape(lambda: variables)
        struct_x = jax.ShapeDtypeStruct(x.shape, x.dtype)
        cost = price_callable(
            lambda v, a: ref.apply(v, a, train=False), struct_v, struct_x,
            name="resnet_quick", kind="model")
        # the fused path saves the inter-op HBM round trips; credit the
        # roofline with the traffic the kernel keeps in VMEM
        return cost.est_seconds * (0.7 if knobs["fused_blocks"] else 1.0)

    def measure(knobs: Dict[str, Any]) -> float:
        model = build(knobs["fused_blocks"])

        def loss_fn(params, batch_stats):
            out = model.apply(
                {"params": params, "batch_stats": batch_stats}, x,
                train=False)
            return jnp.mean(out ** 2)

        grad = jax.jit(jax.grad(loss_fn))
        g = grad(variables["params"], variables["batch_stats"])  # compile
        jax.block_until_ready(g)
        return measure_steps(
            lambda: jax.block_until_ready(
                grad(variables["params"], variables["batch_stats"])),
            steps=steps)

    return sweep("resnet", resnet_quick_candidates(), measure=measure,
                 price=price, keep=2, quick=True)


def gpt_quick_candidates(n_devices: int = 1) -> List[Dict[str, Any]]:
    grid = [
        {"remat": False, "scan_blocks": True},
        {"remat": True, "scan_blocks": True},
        {"remat": False, "scan_blocks": False},
    ]
    if n_devices > 1:
        grid = [dict(g, gather_mode=m) for g in grid
                for m in ("overlap", "eager")]
    return grid


def autotune_gpt_quick(steps: int = 2) -> AutotuneResult:
    """The GPT sweep at toy shape: remat x scan_blocks (x fsdp gather mode
    when the mesh has >1 device), priced by AOT cost of the candidate's own
    train step (remat's recompute shows up in its FLOPs), measured with
    real steps."""
    import jax
    import jax.numpy as jnp
    import optax

    from kubeflow_tpu.models.gpt import GptConfig, GptLM
    from kubeflow_tpu.training.attribution import price_callable

    n_dev = len(jax.devices())
    batch, seq = 2, 32
    ids = jnp.zeros((batch, seq), jnp.int32)

    def build(knobs: Dict[str, Any]):
        cfg = GptConfig(d_model=32, n_layers=2, n_heads=2, d_ff=64,
                        max_seq=seq, vocab_size=64,
                        remat=bool(knobs.get("remat")),
                        scan_blocks=bool(knobs.get("scan_blocks")))
        model = GptLM(cfg)
        params = model.init(jax.random.PRNGKey(0), ids)
        tx = optax.sgd(1e-2)

        def loss_fn(p):
            logits = model.apply(p, ids)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            tgt = jnp.roll(ids, -1, axis=-1)
            return -jnp.mean(jnp.take_along_axis(logp, tgt[..., None], -1))

        def step(p, opt):
            loss, g = jax.value_and_grad(loss_fn)(p)
            updates, opt = tx.update(g, opt, p)
            return optax.apply_updates(p, updates), opt, loss

        return jax.jit(step), params, tx.init(params)

    def build_fsdp(knobs: Dict[str, Any]):
        from kubeflow_tpu.training.fsdp import (
            FsdpConfig, fsdp_batch_sharding, fsdp_mesh, init_fsdp_params,
            make_fsdp_train_step)

        cfg = FsdpConfig(d_model=32, n_layers=2, n_heads=2, d_ff=64,
                         seq=seq, vocab_size=64)
        mesh = fsdp_mesh()
        params = init_fsdp_params(jax.random.PRNGKey(0), cfg, mesh)
        fids = jax.device_put(
            jnp.zeros((max(batch, n_dev), seq), jnp.int32),
            fsdp_batch_sharding(mesh))
        step = make_fsdp_train_step(cfg, mesh,
                                    gather_mode=knobs["gather_mode"])
        return step, params, fids

    def price(knobs: Dict[str, Any]) -> float:
        if "gather_mode" in knobs:
            # collectives are invisible to single-program cost analysis;
            # rank gather modes by measurement only
            raise ValueError("gather_mode is measured, not priced")
        step, params, opt = build(knobs)
        sp = jax.eval_shape(lambda: params)
        so = jax.eval_shape(lambda: opt)
        return price_callable(
            lambda p, o: step(p, o)[2], sp, so,
            name="gpt_quick", kind="model", train_factor=1.0).est_seconds

    def measure(knobs: Dict[str, Any]) -> float:
        if "gather_mode" in knobs:
            step, params, fids = build_fsdp(knobs)
            out = step(params, fids)
            jax.block_until_ready(out)
            return measure_steps(
                lambda: jax.block_until_ready(step(params, fids)),
                steps=steps)
        step, params, opt = build(knobs)
        out = step(params, opt)
        jax.block_until_ready(out)
        return measure_steps(
            lambda: jax.block_until_ready(step(params, opt)), steps=steps)

    # with gather_mode in the grid pricing is per-candidate impossible for
    # the fsdp rows; sweep() records those as price errors and still
    # measures them (pruning only ever drops priced candidates)
    cands = gpt_quick_candidates(n_dev)
    return sweep("gpt", cands, measure=measure,
                 price=None if n_dev > 1 else price,
                 keep=2, quick=True)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--quick", action="store_true",
                        help="toy shapes; the autotune-smoke presubmit path")
    parser.add_argument("--family", choices=("resnet", "gpt", "all"),
                        default="all")
    parser.add_argument("--steps", type=int, default=2)
    args = parser.parse_args(argv)
    if not args.quick:
        parser.error("only --quick is wired for standalone runs; the full "
                     "sweep runs inside bench.py (BENCH_AUTOTUNE=1)")
    out: Dict[str, Any] = {}
    if args.family in ("resnet", "all"):
        out["resnet"] = autotune_resnet_quick(steps=args.steps).to_dict()
    if args.family in ("gpt", "all"):
        out["gpt"] = autotune_gpt_quick(steps=args.steps).to_dict()
    print(json.dumps(out, indent=1, default=str))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
