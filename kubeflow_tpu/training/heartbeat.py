"""Per-worker step beacons — the straggler plane's raw signal.

Every observability layer before this one (goodput ledger, attribution,
trace federation) assumes workers make progress; none can answer the
first question a multi-host operator asks: *which worker is slow, and is
the gang hung?* A :class:`WorkerBeacon` is the per-worker heartbeat that
makes the question answerable: each training step publishes the worker's
step index, incarnation, step wall time, and per-phase split (including
the ``collective_wait`` phase from :meth:`StepClock.collective
<kubeflow_tpu.tpu.profiling.StepClock.collective>`) as
``training_worker_*`` metrics. The monitoring plane scrapes them into the
TSDB; :class:`~kubeflow_tpu.monitoring.stragglers.StragglerDetector`
cross-sections the gang per tick.

The beacon doubles as the chaos plane's worker handle: ``slow_factor``
stretches the worker's per-step pacing and ``wedge()`` parks it inside
:meth:`_wedge_wait` until released — so a chaos-injected hang produces a
stack dump (``runtime/obs.py``) that literally names the wedged frame.

Metric names are constant; per-worker dimensions ride in the ``worker``
label so cardinality is one series per gang member, not per name.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from ..runtime.metrics import METRICS
from ..runtime.obs import register_debug_source

#: the phases a beacon breaks a step into (superset is fine — anything the
#: StepClock measured is forwarded; these always exist, zero when unmeasured)
CANONICAL_PHASES = ("data_wait", "compute", "fetch", "collective_wait")

#: process-global beacon registry backing the ``/debug/beacon`` source —
#: keyed by worker id, last registration per id wins (what per-test and
#: per-incarnation rebuilds need)
_BEACONS: Dict[str, "WorkerBeacon"] = {}
_BEACONS_LOCK = threading.Lock()


class WorkerBeacon:
    """One worker's per-step heartbeat publisher + chaos throttle point.

    ``publish(rec)`` takes a StepClock ``end_step()`` record (or any dict
    with a ``total`` and phase keys) and lands it in the metrics registry;
    ``throttle()`` is the chaos interposition point the workload calls
    under its ``collective_wait`` phase — a slowed worker sleeps there, a
    wedged worker blocks there until released.
    """

    def __init__(
        self,
        worker: str,
        *,
        registry: Any = METRICS,
        step_delay_s: float = 0.0,
        expected_collective_s: Optional[Callable[[], float]] = None,
    ) -> None:
        self.worker = str(worker)
        self._ns = registry.namespace("training_worker")
        #: base per-step pacing (the simulated collective) — chaos multiplies
        self.step_delay_s = float(step_delay_s)
        #: chaos handle: >1.0 stretches every step's pacing sleep
        self.slow_factor = 1.0
        #: chaos handle: set → the worker parks in _wedge_wait until cleared
        self._wedge = threading.Event()
        self._released = threading.Event()
        self._released.set()
        #: analytic collective-wait floor (parallel/comm.py) reported when
        #: the workload has no measured collective phase
        self._expected_collective = expected_collective_s
        self.incarnation = 0
        self.step_index = -1
        self.last_step_at = 0.0
        self.last_rec: Dict[str, float] = {}
        with _BEACONS_LOCK:
            _BEACONS[self.worker] = self

    # -- chaos handles -------------------------------------------------------
    def wedge(self) -> None:
        """Park the worker at its next ``throttle()`` until ``release()``."""
        self._released.clear()
        self._wedge.set()

    def release(self) -> None:
        """Undo ``wedge()`` — the parked worker resumes immediately."""
        self._wedge.clear()
        self._released.set()

    @property
    def wedged(self) -> bool:
        return self._wedge.is_set()

    def _wedge_wait(self) -> None:
        # A dedicated frame so the hang forensics stack dump names it: a
        # wedged worker's dump reads ``... throttle -> _wedge_wait``.
        while not self._released.wait(timeout=0.05):
            pass

    def throttle(self) -> float:
        """The chaos interposition point, called once per step (under the
        workload's ``collective_wait`` phase): applies the pacing sleep
        stretched by ``slow_factor``, then blocks while wedged. Returns the
        wall seconds spent."""
        t0 = time.perf_counter()
        delay = self.step_delay_s * max(1.0, self.slow_factor)
        if delay > 0.0:
            time.sleep(delay)
        if self._wedge.is_set():
            self._wedge_wait()
        return time.perf_counter() - t0

    # -- publishing ----------------------------------------------------------
    def begin_incarnation(self, attempt: int) -> None:
        """A new incarnation restarts the step index from its checkpoint —
        the beacon bumps the incarnation gauge FIRST so the detector can
        tell a restart from a counter going backwards."""
        self.incarnation = int(attempt)
        self.step_index = -1
        self._ns.gauge("incarnation", worker=self.worker).set(float(attempt))

    def publish(self, rec: Dict[str, float], step: Optional[int] = None) -> None:
        """Land one step's record in the registry. ``rec`` is a StepClock
        ``end_step()`` dict (phase seconds + ``total``); ``step`` overrides
        the monotonic local counter (the restore path starts mid-run)."""
        self.step_index = self.step_index + 1 if step is None else int(step)
        total = float(rec.get("total", 0.0))
        now = time.time()
        self.last_step_at = now
        self.last_rec = {k: float(v) for k, v in rec.items()}
        ns = self._ns
        w = self.worker
        ns.counter("step_total", worker=w).inc()
        ns.histogram("step_seconds", worker=w).observe(total)
        ns.gauge("step_wall_seconds", worker=w).set(total)
        ns.gauge("step_index", worker=w).set(float(self.step_index))
        ns.gauge("last_step_timestamp_seconds", worker=w).set(now)
        for phase in CANONICAL_PHASES:
            measured = float(rec.get(phase, 0.0))
            if (
                phase == "collective_wait"
                and measured == 0.0
                and self._expected_collective is not None
            ):
                # no measured collective phase: report the analytic floor
                # (parallel/comm.collective_wait_seconds) so the skew view
                # still has a baseline column
                measured = float(self._expected_collective())
            ns.gauge("phase_seconds", worker=w, phase=phase).set(measured)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "worker": self.worker,
            "incarnation": self.incarnation,
            "stepIndex": self.step_index,
            "lastStepAt": self.last_step_at,
            "slowFactor": self.slow_factor,
            "wedged": self.wedged,
            "lastStep": dict(self.last_rec),
        }


def beacons() -> Dict[str, WorkerBeacon]:
    """The live beacon registry (worker id → beacon), for chaos targeting."""
    with _BEACONS_LOCK:
        return dict(_BEACONS)


def clear_beacons() -> None:
    """Drop all registered beacons (test isolation)."""
    with _BEACONS_LOCK:
        _BEACONS.clear()


def _beacon_source(req: Any) -> Dict[str, Any]:
    """``GET /debug/beacon`` — every registered worker's latest heartbeat."""
    with _BEACONS_LOCK:
        items = list(_BEACONS.values())
    return {"workers": {b.worker: b.snapshot() for b in items}}


register_debug_source("beacon", _beacon_source)
