"""Elastic training: survive preemption, resume on whatever slice is next.

The scheduler's drain protocol (docs/ELASTICITY.md) turns eviction into a
two-phase signal: a drain-deadline annotation lands on the victim's pods,
and deletion waits for an ack or the deadline. This module is the workload
side of that contract — the Podracer discipline (PAPERS.md) of cheap,
preemptible, restartable workers:

- :class:`PreemptionHandler` — polls the gang's pods between steps for the
  drain signal (or their disappearance), and acks once state is safe;
- :class:`ElasticTrainer`   — the supervising loop: train → on drain,
  urgent-checkpoint + ack → on eviction/crash, re-request a gang, accept
  WHATEVER slice the ledger offers next, restore from the latest complete
  checkpoint, keep going;
- :class:`CompositeWorkload` — the composed-4D GPT as an elastic workload:
  snapshots are the canonical per-layer weights
  (``composite.canonical_params``), so a (pp=4, V=1) checkpoint restores
  onto a (pp=2, V=2) mesh by re-chunking, not by luck.

Metrics: ``training_preemptions_survived_total``,
``training_restart_seconds`` (plus ``checkpoint_save_seconds`` from
training/checkpoint.py) — the elastic e2e driver asserts on all three.
Every run also feeds a :class:`~kubeflow_tpu.monitoring.goodput.GoodputLedger`
(scheduling_wait / checkpoint_restore / reshard / checkpoint_save intervals,
per-step goodput-vs-replay attribution) — the goodput e2e driver asserts the
decomposition reconciles against its own wallclock measurement.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..api import meta as apimeta
from ..monitoring.goodput import GoodputLedger
from ..runtime.metrics import METRICS
from ..scheduler.gang import (
    DRAIN_ACK_ANNOTATION,
    DRAIN_DEADLINE_ANNOTATION,
    is_terminal,
)
from .checkpoint import Checkpointer

LOG = logging.getLogger(__name__)
TRAIN = METRICS.namespace("training")


@dataclass(frozen=True)
class DrainStatus:
    """What the gang's pods say about this incarnation's future."""

    state: str  # "ok" | "draining" | "lost"
    deadline: Optional[float] = None  # unix seconds, when draining


@dataclass(frozen=True)
class SliceOffer:
    """One gang's worth of capacity the ledger granted us — whatever shape
    it happens to be. ``devices`` are the local jax devices backing it (in
    the dryrun harness: a subset of the virtual CPU devices sized like the
    slice); (pp, virtual_stages) is the factorization the workload should
    rebuild for."""

    devices: Sequence[Any]
    pp: int = 1
    virtual_stages: int = 1
    pods: Sequence[str] = ()
    namespace: Optional[str] = None

    def describe(self) -> Dict[str, Any]:
        return {
            "chips": len(self.devices),
            "pp": self.pp,
            "virtualStages": self.virtual_stages,
            "pods": list(self.pods),
        }


class PreemptionHandler:
    """Between-step watcher for the drain protocol on a gang's pods.

    ``check()`` is called once per training step, so the apiserver sweep is
    rate-limited to ``poll_interval``; a drain verdict is sticky (once
    draining, always draining — the scheduler never un-asks).
    ``request_local_drain`` injects the same signal in-process, used by the
    chaos harness to exercise the handler without a scheduler.
    """

    def __init__(
        self,
        client,
        namespace: Optional[str],
        pod_names: Sequence[str],
        poll_interval: float = 0.05,
    ) -> None:
        self._client = client
        self._namespace = namespace
        self._pods = list(pod_names)
        self._poll_interval = poll_interval
        self._last_poll = 0.0
        self._cached = DrainStatus("ok")

    def request_local_drain(self, grace: float = 5.0) -> None:
        self._cached = DrainStatus("draining", time.time() + grace)

    def check(self) -> DrainStatus:
        if self._cached.state == "draining":
            return self._cached
        now = time.monotonic()
        if now - self._last_poll < self._poll_interval:
            return self._cached
        self._last_poll = now
        self._cached = self._sweep()
        return self._cached

    def _sweep(self) -> DrainStatus:
        live = 0
        deadline: Optional[float] = None
        for name in self._pods:
            pod = self._client.get_opt("v1", "Pod", name, self._namespace)
            if pod is None or is_terminal(pod):
                continue
            live += 1
            raw = apimeta.annotations_of(pod).get(DRAIN_DEADLINE_ANNOTATION)
            if raw is not None:
                try:
                    d = float(raw)
                except (TypeError, ValueError):
                    d = time.time()
                deadline = d if deadline is None else min(deadline, d)
        if deadline is not None:
            return DrainStatus("draining", deadline)
        if live == 0 and self._pods:
            # gang gone without a drain signal — killed node, hard crash
            return DrainStatus("lost")
        return DrainStatus("ok")

    def ack(self, step: int) -> None:
        """Tell the scheduler our state is safe: it may evict immediately
        instead of waiting out the grace deadline."""
        for name in self._pods:
            try:
                self._client.patch(
                    "v1", "Pod", name,
                    {"metadata": {"annotations": {DRAIN_ACK_ANNOTATION: str(step)}}},
                    self._namespace,
                )
            except Exception:  # pod already deleted: the ack is moot
                continue


@dataclass
class ElasticReport:
    """What one ``ElasticTrainer.run()`` lived through."""

    completed: bool
    losses: Dict[int, float] = field(default_factory=dict)
    preemptions_survived: int = 0
    restarts: int = 0
    incarnations: List[Dict[str, Any]] = field(default_factory=list)


class ElasticTrainer:
    """Supervising loop: (re)acquire a slice, restore-or-init, train until
    drained/lost/done, checkpoint, repeat.

    The workload is pluggable (duck-typed):

    - ``init(offer) -> state``
    - ``restore(offer, snapshot, meta) -> state``   (re-chunk for the offer)
    - ``snapshot(state) -> (tree, meta)``           (factorization-free)
    - ``run_step(state, step) -> (state, loss)``    (data chosen BY step, so
      replayed steps reproduce the same curve)

    ``slice_provider(attempt)`` blocks until the ledger grants a gang and
    returns a :class:`SliceOffer` (or None to give up);
    ``handler_factory(offer)`` builds the :class:`PreemptionHandler`-shaped
    watcher for that gang (None disables drain detection).
    """

    def __init__(
        self,
        workload,
        checkpointer: Checkpointer,
        slice_provider: Callable[[int], Optional[SliceOffer]],
        total_steps: int,
        *,
        checkpoint_every: int = 0,
        handler_factory: Optional[Callable[[SliceOffer], Any]] = None,
        max_incarnations: int = 32,
        goodput: Optional[GoodputLedger] = None,
    ) -> None:
        self.workload = workload
        self.ckpt = checkpointer
        self.slice_provider = slice_provider
        self.total_steps = int(total_steps)
        self.checkpoint_every = int(checkpoint_every)
        self.handler_factory = handler_factory
        self.max_incarnations = int(max_incarnations)
        self.goodput = goodput if goodput is not None else GoodputLedger()

    def run(self) -> ElasticReport:
        report = ElasticReport(completed=False)
        gp = self.goodput
        gp.start()
        step_clock = getattr(self.workload, "clock", None)
        if step_clock is not None:
            gp.attach_step_clock(step_clock)
        try:
            return self._run(report)
        finally:
            gp.finish()

    def _run(self, report: ElasticReport) -> ElasticReport:
        gp = self.goodput
        beacon = getattr(self.workload, "beacon", None)
        for attempt in range(self.max_incarnations):
            t0 = time.perf_counter()
            gp.begin_incarnation(attempt)
            if beacon is not None:
                # incarnation gauge bumps BEFORE any step publishes, so the
                # straggler detector reads restart-then-step-reset in order
                beacon.begin_incarnation(attempt)
            offer = self.slice_provider(attempt)
            if offer is None:
                break
            gp.note("scheduling_wait", time.perf_counter() - t0)
            state, start = self._restore_or_init(offer)
            handler = self.handler_factory(offer) if self.handler_factory else None
            if attempt > 0:
                # acquire + restore + reshard — the restart cost the chaos
                # driver bounds
                TRAIN.histogram("restart_seconds").observe(time.perf_counter() - t0)
                report.restarts += 1
            inc = {"attempt": attempt, "startStep": start, "offer": offer.describe()}
            report.incarnations.append(inc)
            outcome, end_step = self._train(state, start, handler, report)
            inc["outcome"] = outcome
            inc["endStep"] = end_step
            inc["goodput"] = gp.end_incarnation(outcome, end_step)
            if outcome == "completed":
                report.completed = True
                return report
            LOG.warning(
                "elastic: incarnation %d ended %s at step %d; re-requesting slice",
                attempt, outcome, end_step,
            )
        return report

    # -- one incarnation -----------------------------------------------------
    def _restore_or_init(self, offer: SliceOffer) -> Tuple[Any, int]:
        t0 = time.perf_counter()
        try:
            snap, meta = self.ckpt.restore_numpy()
        except FileNotFoundError:
            # nothing to read — first incarnation's build is mesh/step_fn
            # setup for the offered shape, i.e. the reshard bucket
            t1 = time.perf_counter()
            state = self.workload.init(offer)
            self.goodput.note("reshard", time.perf_counter() - t1)
            return state, 0
        self.goodput.note("checkpoint_restore", time.perf_counter() - t0)
        t1 = time.perf_counter()
        state = self.workload.restore(offer, snap, meta)
        self.goodput.note("reshard", time.perf_counter() - t1)
        return state, int(meta.get("step", -1)) + 1

    def _train(self, state, start: int, handler, report: ElasticReport):
        step = start
        while step < self.total_steps:
            s0 = time.perf_counter()
            state, loss = self.workload.run_step(state, step)
            self.goodput.step(step, time.perf_counter() - s0)
            report.losses[step] = float(loss)
            if self.checkpoint_every and (step + 1) % self.checkpoint_every == 0:
                self._save(state, step)
            status = handler.check() if handler is not None else DrainStatus("ok")
            if status.state == "draining":
                # the urgent save: everything up to and including this step
                # survives the eviction, so zero steps are lost
                self._save(state, step)
                if handler is not None:
                    handler.ack(step)
                TRAIN.counter("preemptions_survived_total").inc()
                report.preemptions_survived += 1
                return "preempted", step
            if status.state == "lost":
                # no drain, no save: the next incarnation replays from the
                # last periodic checkpoint
                return "lost", step
            step += 1
        return "completed", step

    def _save(self, state, step: int) -> None:
        t0 = time.perf_counter()
        snap, wmeta = self.workload.snapshot(state)
        meta = {"step": step}
        meta.update(wmeta or {})
        self.ckpt.save(step, snap, meta=meta)
        self.goodput.note("checkpoint_save", time.perf_counter() - t0)


class CompositeWorkload:
    """The composed-4D pipeline GPT (parallel/composite.py) as an elastic
    workload. The snapshot is the CANONICAL per-layer weight tree, so every
    incarnation rebuilds its own (pp, virtual_stages) chunking from it —
    restoring a (pp=4, V=1) checkpoint on a (pp=2, V=2) mesh is the same
    logical model continuing its loss curve.

    Batches are derived from the step index (seeded), never from an
    in-memory iterator, so the data pipeline "cursor" in the checkpoint
    meta is just the step — replay after restore sees identical data.

    With a ``clock`` (``tpu.profiling.StepClock``) the workload phases its
    step body — batch synthesis under ``data_wait``, a one-time AOT compile
    per incarnation under ``compile``, execution under ``compute``, the loss
    readback under ``fetch`` — so the goodput ledger can drain compile and
    data-wait time out of step wall time into their own badput buckets.
    """

    def __init__(
        self,
        cfg=None,
        *,
        lr: float = 0.1,
        num_micro: int = 4,
        microbatch: int = 4,
        data_seed: int = 0,
        init_seed: int = 0,
        gather_mode: str = "eager",
        clock: Optional[Any] = None,
        beacon: Optional[Any] = None,
    ) -> None:
        from ..parallel.composite import CompositeConfig

        self.cfg = cfg or CompositeConfig()
        self.lr = lr
        self.num_micro = num_micro
        self.microbatch = microbatch
        self.data_seed = data_seed
        self.init_seed = init_seed
        self.gather_mode = gather_mode
        self.clock = clock
        #: training.heartbeat.WorkerBeacon — per-step heartbeat + the chaos
        #: plane's throttle point (slow_worker / wedge_worker land here)
        self.beacon = beacon

    def _setup(self, offer: SliceOffer):
        from ..parallel.composite import make_train_step
        from ..parallel.mesh import MeshConfig, make_mesh

        mesh = make_mesh(
            MeshConfig(data=-1, pipe=offer.pp), devices=list(offer.devices)
        )
        step_fn = make_train_step(
            self.cfg, mesh, self.lr,
            virtual_stages=offer.virtual_stages, gather_mode=self.gather_mode,
        )
        return mesh, step_fn

    def init(self, offer: SliceOffer):
        import jax

        from ..parallel.composite import init_params

        mesh, step_fn = self._setup(offer)
        params = init_params(
            jax.random.PRNGKey(self.init_seed), self.cfg, mesh,
            virtual_stages=offer.virtual_stages,
        )
        return {"mesh": mesh, "step_fn": step_fn, "params": params, "offer": offer}

    def restore(self, offer: SliceOffer, snap, meta):
        from ..parallel.composite import params_from_canonical

        mesh, step_fn = self._setup(offer)
        params = params_from_canonical(
            snap["params"], self.cfg, mesh, virtual_stages=offer.virtual_stages
        )
        return {"mesh": mesh, "step_fn": step_fn, "params": params, "offer": offer}

    def snapshot(self, state):
        from ..parallel.composite import canonical_params

        canon = canonical_params(
            state["params"], state["mesh"],
            virtual_stages=state["offer"].virtual_stages,
        )
        offer = state["offer"]
        return {"params": canon}, {
            "pp": offer.pp,
            "virtualStages": offer.virtual_stages,
            "dataCursor": None,  # data is step-addressed; the step IS the cursor
        }

    def _batch(self, state, step: int):
        import jax
        import numpy as np

        from ..parallel.composite import batch_sharding

        rng = np.random.RandomState(self.data_seed + step)
        ids = rng.randint(
            0, self.cfg.vocab_size,
            size=(self.num_micro, self.microbatch, self.cfg.seq),
        ).astype(np.int32)
        return jax.device_put(ids, batch_sharding(state["mesh"]))

    def run_step(self, state, step: int):
        if self.clock is None:
            t0 = time.perf_counter()
            params, loss = state["step_fn"](state["params"], self._batch(state, step))
            state["params"] = params
            if self.beacon is not None:
                wait = self.beacon.throttle()
                self.beacon.publish(
                    {"total": time.perf_counter() - t0, "collective_wait": wait},
                    step,
                )
            return state, float(loss)
        clock = self.clock
        with clock.data_wait():
            batch = self._batch(state, step)
        if not state.get("warm"):
            # one AOT compile per incarnation so XLA time lands in the
            # clock's separate compile accumulator, never in a step
            with clock.compile():
                try:
                    state["step_fn"] = (
                        state["step_fn"].lower(state["params"], batch).compile()
                    )
                except AttributeError:  # already an AOT executable
                    pass
            state["warm"] = True
        with clock.compute():
            params, loss = state["step_fn"](state["params"], batch)
        with clock.fetch():
            loss = float(loss)
        if self.beacon is not None:
            # the gradient-sync barrier stand-in: a slowed/wedged worker
            # parks HERE, inside the measured collective_wait phase
            with clock.collective():
                self.beacon.throttle()
        rec = clock.end_step()
        if self.beacon is not None:
            self.beacon.publish(rec, step)
        state["params"] = params
        return state, loss
