"""Training harness: sharded train steps, schedules, MFU accounting.

The reference delegates training to workload CRs (SURVEY.md §2.10); this is
the in-workload half that the BASELINE north-star measures (ResNet-50 MFU).
Everything compiles to one XLA program per step: optimizer update included,
donated state, shardings from kubeflow_tpu.parallel.
"""

from kubeflow_tpu.training.checkpoint import Checkpointer  # noqa: F401
from kubeflow_tpu.training.elastic import (  # noqa: F401
    CompositeWorkload,
    DrainStatus,
    ElasticReport,
    ElasticTrainer,
    PreemptionHandler,
    SliceOffer,
)
from kubeflow_tpu.training.classifier import (  # noqa: F401
    ClassifierTask,
    TrainState,
    cross_entropy_loss,
)
from kubeflow_tpu.training.flops import (  # noqa: F401
    compiled_flops,
    compiled_with_cost,
    memory_stats,
    mfu,
    peak_hbm_bandwidth,
)
from kubeflow_tpu.training.attribution import (  # noqa: F401
    AttributionReport,
    ModuleCost,
    attribute_gpt,
    attribute_resnet,
    attribution_report,
    price_callable,
    record_step_peak_hbm,
)
from kubeflow_tpu.training.autotune import (  # noqa: F401
    AutotuneResult,
    TunedCandidate,
    autotune_gpt_quick,
    autotune_resnet_quick,
    measure_steps,
    sweep,
)
from kubeflow_tpu.training.fsdp import (  # noqa: F401
    FSDP_GATHER_MODES,
    FsdpConfig,
    fsdp_batch_sharding,
    fsdp_mesh,
    init_fsdp_params,
    make_fsdp_train_step,
)
