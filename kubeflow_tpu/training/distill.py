"""Draft distillation for speculative decoding (ISSUE 18).

The self-speculative draft (the target's own first ``n_layers // 4``
blocks, e2e/serving_bench.py) accepts only what the truncated stack
happens to agree with the full stack about — r06 measured
``spec_accept_rate`` ~0.14, so most drafted tokens were verification
waste. This module trains a SMALL draft to imitate the target where
acceptance is actually scored: along the target's own greedy decode
trajectories.

Recipe (on-policy KL distillation):

1. build a corpus by running the TARGET's greedy decode from random
   prompts — the sequences speculative decoding will actually walk,
2. warm-start the draft from the target's bottom blocks + embeddings
   (the same initialization the self-draft uses, so the distilled draft
   strictly dominates it),
3. minimize ``KL(teacher || student)`` over every corpus position with
   Adam; the teacher forward runs under ``stop_gradient`` semantics
   (its logits are data).

Greedy acceptance only needs the draft's ARGMAX to match, which on-policy
KL achieves quickly: decode trajectories concentrate on a narrow token
set, so a 1-2 block student saturates them in a few hundred steps. The
result checkpoints through the PR 7 :class:`Checkpointer` (per-leaf
manifest + crc32), and ``(draft_cfg, draft_params)`` plugs straight into
``ContinuousBatcher(spec_draft=...)``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..models.gpt import GptConfig, GptLM, generate
from ..runtime.metrics import METRICS


def draft_config(cfg: GptConfig, n_layers: Optional[int] = None) -> GptConfig:
    """The draft's shape: the target's width at ``n_layers`` depth
    (default ``max(1, n_layers // 4)`` — the self-draft's depth, so the
    distilled draft is a drop-in replacement at identical step cost)."""
    return GptConfig(d_model=cfg.d_model,
                     n_layers=n_layers or max(1, cfg.n_layers // 4),
                     n_heads=cfg.n_heads, d_ff=cfg.d_ff,
                     max_seq=cfg.max_seq, vocab_size=cfg.vocab_size)


def init_from_target(draft_cfg: GptConfig, params: Any) -> Any:
    """Warm-start draft params: the target's embeddings, final norm, and
    bottom ``draft_cfg.n_layers`` blocks — exactly the self-draft's
    parameter set, copied so training cannot touch the target."""
    draft_params = {k: v for k, v in params.items()
                    if not k.startswith("block_")}
    for i in range(draft_cfg.n_layers):
        draft_params[f"block_{i}"] = params[f"block_{i}"]
    return jax.tree_util.tree_map(jnp.asarray, draft_params)


def _decode_corpus(cfg: GptConfig, params: Any, *, sequences: int,
                   prompt_len: int, decode_len: int, seed: int) -> np.ndarray:
    """[sequences, prompt_len + decode_len] token ids: random prompts
    continued by the TARGET's greedy decode — the trajectories speculative
    verification will score the draft on."""
    rng = jax.random.PRNGKey(seed)
    prompts = jax.random.randint(rng, (sequences, prompt_len), 0,
                                 cfg.vocab_size)
    return np.asarray(generate(cfg, params, prompts,
                               max_new_tokens=decode_len))


def distill_draft(cfg: GptConfig, params: Any,
                  draft_cfg: Optional[GptConfig] = None, *,
                  steps: int = 300, batch: int = 8, sequences: int = 32,
                  prompt_len: int = 16, decode_len: int = 48,
                  lr: float = 1e-3, kl_temperature: float = 1.0,
                  seed: int = 0,
                  checkpoint_dir: Optional[str] = None
                  ) -> Tuple[GptConfig, Any]:
    """Distill a draft from ``(cfg, params)``; returns
    ``(draft_cfg, draft_params)`` ready for ``spec_draft=``.

    ``checkpoint_dir`` persists the result through the canonical
    :class:`~kubeflow_tpu.training.checkpoint.Checkpointer` with a meta
    record of the recipe; a later process restores it with
    ``Checkpointer(dir).restore_numpy()``.
    """
    draft_cfg = draft_cfg or draft_config(cfg)
    if (draft_cfg.vocab_size != cfg.vocab_size
            or draft_cfg.max_seq != cfg.max_seq):
        raise ValueError("draft must share the target's vocab and max_seq")
    corpus = _decode_corpus(cfg, params, sequences=sequences,
                            prompt_len=prompt_len,
                            decode_len=min(decode_len,
                                           cfg.max_seq - prompt_len),
                            seed=seed)
    target = GptLM(cfg)
    draft = GptLM(draft_cfg)
    draft_params = init_from_target(draft_cfg, params)
    tx = optax.adam(lr)
    opt_state = tx.init(draft_params)
    temp = float(kl_temperature)

    @jax.jit
    def teacher_logits(ids):
        return jax.lax.stop_gradient(target.apply({"params": params}, ids))

    @jax.jit
    def step_fn(dp, opt, ids, tlogits):
        def loss_fn(p):
            slogits = draft.apply({"params": p}, ids)
            t = jax.nn.log_softmax(tlogits.astype(jnp.float32) / temp, -1)
            s = jax.nn.log_softmax(slogits.astype(jnp.float32) / temp, -1)
            # KL(teacher || student), averaged over batch x positions; the
            # prompt positions train the draft's prefill representation,
            # the decode positions are what acceptance scores
            return jnp.mean(jnp.sum(jnp.exp(t) * (t - s), axis=-1))

        loss, grads = jax.value_and_grad(loss_fn)(dp)
        updates, opt = tx.update(grads, opt, dp)
        return optax.apply_updates(dp, updates), opt, loss

    rng = np.random.default_rng(seed + 1)
    loss = None
    for _ in range(int(steps)):
        rows = rng.integers(0, corpus.shape[0], size=batch)
        ids = jnp.asarray(corpus[rows])
        dp_new, opt_state, loss = step_fn(draft_params, opt_state, ids,
                                          teacher_logits(ids))
        draft_params = dp_new
        METRICS.counter("distill_steps_total").inc()
    final_kl = float(loss) if loss is not None else 0.0
    METRICS.gauge("distill_kl").set(final_kl)

    if checkpoint_dir:
        from .checkpoint import Checkpointer

        meta: Dict[str, Any] = {
            "kind": "spec_draft",
            "distilled_from": {"d_model": cfg.d_model,
                               "n_layers": cfg.n_layers,
                               "vocab_size": cfg.vocab_size},
            "draft_layers": draft_cfg.n_layers,
            "steps": int(steps), "lr": lr, "seed": seed,
            "final_kl": round(final_kl, 6),
        }
        Checkpointer(checkpoint_dir).save(int(steps), draft_params,
                                          meta=meta)
    return draft_cfg, draft_params


def measure_accept_rate(cfg: GptConfig, params: Any,
                        draft_cfg: GptConfig, draft_params: Any, *,
                        n_requests: int = 8, prompt_len: int = 16,
                        budget: int = 32, spec_k: int = 4,
                        slots: int = 4, seed: int = 100) -> float:
    """Drive a speculative engine over greedy requests and return the
    measured accept rate (accepted / drafted, straight from the serving
    counters) — the number the bench gate floors."""
    from ..serving.continuous import ContinuousBatcher

    drafted0 = METRICS.counter("serving_spec_tokens_drafted_total").value
    accepted0 = METRICS.counter("serving_spec_tokens_accepted_total").value
    eng = ContinuousBatcher(cfg, params, slots=slots,
                            spec_draft=(draft_cfg, draft_params),
                            spec_k=spec_k)
    try:
        prompts = [np.asarray(jax.random.randint(
            jax.random.PRNGKey(seed + i), (prompt_len,), 0, cfg.vocab_size))
            for i in range(n_requests)]
        futs = [eng.submit(p, budget) for p in prompts]
        for f in futs:
            f.result(timeout=600)
    finally:
        eng.close()
    drafted = METRICS.counter("serving_spec_tokens_drafted_total").value - drafted0
    accepted = METRICS.counter("serving_spec_tokens_accepted_total").value - accepted0
    return accepted / drafted if drafted else 0.0
