"""FLOP accounting and MFU, from the compiler rather than hand math.

XLA's cost analysis on the *compiled* executable counts the FLOPs actually
scheduled (fused, rematerialized, whatever) — the honest numerator for
MFU = flops_per_step / (step_seconds * peak_flops). Peak comes from the
accelerator catalog (kubeflow_tpu.tpu.topology) so control plane and
benchmark agree on the denominator.
"""

from __future__ import annotations

import time
from typing import Any, Optional, Tuple

import jax

from kubeflow_tpu.tpu.topology import ACCELERATORS


def _flops_of(compiled: Any) -> Optional[float]:
    analysis = compiled.cost_analysis()
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not analysis:
        return None
    flops = analysis.get("flops")
    return float(flops) if flops and flops > 0 else None


def compiled_flops(jitted_fn: Any, *args: Any, **kwargs: Any) -> Optional[float]:
    """Total FLOPs of one invocation, from XLA cost analysis (None if the
    backend doesn't report)."""
    return _flops_of(jitted_fn.lower(*args, **kwargs).compile())


def compiled_with_cost(
    jitted_fn: Any, *args: Any, **kwargs: Any
) -> Tuple[Any, Optional[float], float]:
    """Lower + compile once, returning ``(compiled, flops, compile_seconds)``.

    One AOT compile serves both the callable the bench loop runs and the
    cost analysis — the old ``compiled_flops`` + warmup-call pattern paid
    the (minutes-scale on big configs) XLA compile twice and folded it into
    the first timed window. The compile wall time comes back separately so
    telemetry (StepClock.compile / bench ``step_breakdown``) reports it
    instead of charging it to steps.
    """
    start = time.perf_counter()
    compiled = jitted_fn.lower(*args, **kwargs).compile()
    compile_s = time.perf_counter() - start
    return compiled, _flops_of(compiled), compile_s


def memory_stats(compiled: Any) -> Optional[dict]:
    """HBM footprint of a compiled executable, from the compiler's
    ``memory_analysis`` (the honest counterpart to cost-analysis FLOPs):
    argument/output/temp bytes plus their sum as ``peak_hbm_bytes`` — the
    live-bytes bound the executable needs resident, the number the
    ``training_step_peak_hbm_bytes`` gauge and bench rows report. Returns
    None when the backend doesn't implement the analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    fields = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "generated_code_size_in_bytes")
    try:
        out = {f.replace("_size_in_bytes", "_bytes"): int(getattr(ma, f))
               for f in fields}
    except (AttributeError, TypeError):
        return None
    out["peak_hbm_bytes"] = sum(out.values())
    return out


def peak_flops_per_chip(generation: str = "v5e") -> float:
    return ACCELERATORS[generation].bf16_tflops_per_chip * 1e12


def peak_hbm_bandwidth(generation: str = "v5e") -> float:
    """Peak HBM bytes/second per chip — the roofline's memory ceiling."""
    return ACCELERATORS[generation].hbm_gbps_per_chip * 1e9


def mfu(
    flops_per_step: float,
    step_seconds: float,
    num_chips: int = 1,
    generation: str = "v5e",
) -> float:
    """Model FLOPs utilization in [0, 1]."""
    return flops_per_step / (step_seconds * num_chips * peak_flops_per_chip(generation))


def detect_generation(default: str = "v5e") -> str:
    """Map the live JAX device to a catalog generation (bench runs)."""
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return default
    for gen in ACCELERATORS:
        if gen in kind.replace(" ", "").replace("lite", "e"):
            return gen
    if "v5 lite" in kind or "v5lite" in kind:
        return "v5e"
    if "v6" in kind:
        return "v6e"
    if "v4" in kind:
        return "v4"
    if "v5" in kind:
        return "v5p"
    return default
