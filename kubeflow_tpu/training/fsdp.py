"""Plain FSDP (ZeRO-3) GPT train step with overlapped weight gathers.

``parallel/composite.py`` proved the ``gather_mode="overlap"`` idiom inside
the full dp x fsdp x tp x pp composition: the per-layer weight all_gather
is prefetched one layer ahead in a double-buffered ``lax.scan`` carry, so
the collective has no data dependence on the current layer's matmuls and
the compiler overlaps them (async collectives on TPU). This module applies
the same idiom to the common single-axis case — the "plain" FSDP job the
bench runs when there is no tensor or pipeline parallelism: one ``fsdp``
mesh axis shared by the batch and the weight shards, weights gathered at
use, gradients transposed into reduce_scatters by autodiff (the ZeRO-3
contract).

Modes (:data:`FSDP_GATHER_MODES`):

- ``"eager"``   — gather each layer's weights right before use (baseline;
  the gather sits on the critical path in front of every layer),
- ``"overlap"`` — double-buffered prefetch, one layer ahead; the final
  iteration prefetches a clamped duplicate that is discarded.

Both modes are numerically identical (same math, different comm placement)
— tests/test_fsdp.py asserts the parity. The autotuner
(``training/autotune.py``) sweeps this knob for multi-device GPT configs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel._compat import shard_map_unchecked
from kubeflow_tpu.parallel.mesh import AXIS_FSDP

FSDP_GATHER_MODES = ("eager", "overlap")


@dataclass(frozen=True)
class FsdpConfig:
    vocab_size: int = 256
    d_model: int = 32
    n_heads: int = 4
    d_ff: int = 64
    n_layers: int = 4
    seq: int = 16


def _block_specs() -> Dict[str, P]:
    """Layer-stacked [L, ...] weight shards: the largest non-layer dim goes
    over ``fsdp`` (ZeRO-3); layernorm scales are tiny and stay replicated."""
    return {
        "ln1": P(None, None),
        "ln2": P(None, None),
        "wqkv": P(None, AXIS_FSDP, None, None),   # [L, d, 3, d]
        "wo": P(None, None, AXIS_FSDP),           # [L, d, d]
        "w1": P(None, AXIS_FSDP, None),           # [L, d, ff]
        "w2": P(None, None, AXIS_FSDP),           # [L, ff, d]
    }


def fsdp_mesh(devices=None) -> Mesh:
    """A single-axis ``fsdp`` mesh over all (or the given) devices — the
    plain data-parallel/ZeRO-3 topology."""
    import numpy as np

    devs = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devs), (AXIS_FSDP,))


def init_fsdp_params(rng: jax.Array, cfg: FsdpConfig, mesh: Mesh) -> Dict[str, Any]:
    d, ff, nl = cfg.d_model, cfg.d_ff, cfg.n_layers
    ks = jax.random.split(rng, 5)
    scale = d ** -0.5
    blocks = {
        "ln1": jnp.ones((nl, d), jnp.float32),
        "ln2": jnp.ones((nl, d), jnp.float32),
        "wqkv": jax.random.normal(ks[0], (nl, d, 3, d), jnp.float32) * scale,
        "wo": jax.random.normal(ks[1], (nl, d, d), jnp.float32) * scale,
        "w1": jax.random.normal(ks[2], (nl, d, ff), jnp.float32) * scale,
        "w2": jax.random.normal(ks[3], (nl, ff, d), jnp.float32) * (ff ** -0.5),
    }
    specs = _block_specs()
    blocks = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in blocks.items()}
    embed = jax.device_put(
        jax.random.normal(ks[4], (cfg.vocab_size, d), jnp.float32) * scale,
        NamedSharding(mesh, P(AXIS_FSDP, None)))
    return {"embed": embed, "blocks": blocks}


def fsdp_param_shardings(cfg: FsdpConfig, mesh: Mesh) -> Dict[str, Any]:
    specs = _block_specs()
    return {
        "embed": NamedSharding(mesh, P(AXIS_FSDP, None)),
        "blocks": {k: NamedSharding(mesh, s) for k, s in specs.items()},
    }


def _gather_layer(wqkv_l, wo_l, w1_l, w2_l):
    """all_gather one layer's fsdp shards to full size; autodiff transposes
    each tiled gather into a gradient reduce_scatter (ZeRO-3)."""
    return (
        lax.all_gather(wqkv_l, AXIS_FSDP, axis=0, tiled=True),
        lax.all_gather(wo_l, AXIS_FSDP, axis=1, tiled=True),
        lax.all_gather(w1_l, AXIS_FSDP, axis=0, tiled=True),
        lax.all_gather(w2_l, AXIS_FSDP, axis=1, tiled=True),
    )


def _block(cfg: FsdpConfig, h, ln1, ln2, wqkv, wo, w1, w2):
    """One pre-LN transformer block, weights fully gathered (no tp axis)."""

    def ln(x, scale):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + 1e-5) * scale

    x = ln(h, ln1)
    qkv = jnp.einsum("bsd,drh->bsrh", x, wqkv)           # [b, s, 3, d]
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    hd = cfg.d_model // cfg.n_heads
    b, s, _ = q.shape
    q = q.reshape(b, s, cfg.n_heads, hd).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, cfg.n_heads, hd).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, cfg.n_heads, hd).transpose(0, 2, 1, 3)
    scores = (q @ k.transpose(0, 1, 3, 2)) * (hd ** -0.5)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, scores, -1e30)
    attn = jax.nn.softmax(scores, axis=-1) @ v
    attn = attn.transpose(0, 2, 1, 3).reshape(b, s, cfg.d_model)
    h = h + attn @ wo
    x = ln(h, ln2)
    return h + jax.nn.gelu(x @ w1) @ w2


def _stack_fn(cfg: FsdpConfig, p: Dict[str, jax.Array], h: jax.Array,
              *, gather_mode: str) -> jax.Array:
    """The layer stack under shard_map: ``p`` leaves are LOCAL shards
    [L, ...]; ``h`` is the local batch slice [b_local, seq, d]."""
    lns = (p["ln1"], p["ln2"])
    ws = (p["wqkv"], p["wo"], p["w1"], p["w2"])
    nl = p["ln1"].shape[0]

    if gather_mode == "overlap":

        def gather_at(i):
            return _gather_layer(
                *(lax.dynamic_index_in_dim(w, i, keepdims=False) for w in ws))

        def body(carry, i):
            h, g = carry
            # Issue layer i+1's gathers BEFORE touching layer i's weights:
            # no data dependence on the block compute, so the collectives
            # run concurrently with the matmuls. The last iteration
            # prefetches a clamped duplicate that is discarded.
            g_next = gather_at(jnp.minimum(i + 1, nl - 1))
            ln1, ln2 = (lax.dynamic_index_in_dim(s, i, keepdims=False)
                        for s in lns)
            h = _block(cfg, h, ln1, ln2, *g)
            return (h, g_next), None

        (h, _), _ = lax.scan(body, (h, gather_at(0)), jnp.arange(nl))
        return h

    def block(h, layer):
        ln1, ln2, wqkv_l, wo_l, w1_l, w2_l = layer
        wqkv, wo, w1, w2 = _gather_layer(wqkv_l, wo_l, w1_l, w2_l)
        return _block(cfg, h, ln1, ln2, wqkv, wo, w1, w2), None

    h, _ = lax.scan(block, h, lns + ws)
    return h


def make_fsdp_train_step(cfg: FsdpConfig, mesh: Mesh, lr: float = 0.1,
                         *, gather_mode: str = "overlap"):
    """jit-able (params, ids[B, seq]) -> (params, loss): one SGD step of
    next-token CE under plain ZeRO-3. The batch and the weight shards live
    on the same ``fsdp`` axis; ``gather_mode`` picks where the per-layer
    all_gathers run (see module docstring)."""
    if gather_mode not in FSDP_GATHER_MODES:
        raise ValueError(
            f"gather_mode must be one of {FSDP_GATHER_MODES}, got {gather_mode!r}")
    specs = _block_specs()
    h_spec = P(AXIS_FSDP, None, None)

    stack = shard_map_unchecked(
        lambda p, hh: _stack_fn(cfg, p, hh, gather_mode=gather_mode),
        mesh=mesh,
        in_specs=(specs, h_spec),
        out_specs=h_spec,
    )

    def loss_fn(params, ids):
        # GSPMD region: embedding lookup + loss head; the layer stack is
        # manual SPMD inside the shard_map.
        h = jnp.take(params["embed"], ids, axis=0)       # [B, s, d]
        h = stack(params["blocks"], h)
        logits = h @ params["embed"].T                   # [B, s, vocab]
        targets = jnp.roll(ids, -1, axis=-1)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], axis=-1))

    def step(params, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    in_sharding = (fsdp_param_shardings(cfg, mesh),
                   NamedSharding(mesh, P(AXIS_FSDP, None)))
    return jax.jit(step, in_shardings=in_sharding,
                   out_shardings=(in_sharding[0], NamedSharding(mesh, P())))


def fsdp_batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(AXIS_FSDP, None))
