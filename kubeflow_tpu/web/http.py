"""Tiny threaded HTTP app: routing with path params, JSON bodies, middleware.

Route patterns use ``<name>`` segments (``/api/namespaces/<ns>/notebooks``),
matching the reference crud-backend URL shapes
(crud-web-apps/jupyter/backend/apps/default/routes/post.py:11). Servers bind
port 0 in tests and expose ``server.port``.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from dataclasses import dataclass, field
from http.cookies import SimpleCookie
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

log = logging.getLogger("kubeflow_tpu.web")


class HttpError(Exception):
    """``headers`` ride onto the error response — e.g. ``Retry-After`` on
    an overload 503, so shedding tells clients WHEN to come back."""

    def __init__(self, status: int, message: str,
                 headers: Optional[Dict[str, str]] = None):
        super().__init__(message)
        self.status = status
        self.message = message
        self.headers = dict(headers or {})


@dataclass
class Request:
    method: str
    path: str
    query: Dict[str, List[str]]
    headers: Dict[str, str]
    body: bytes
    params: Dict[str, str] = field(default_factory=dict)
    context: Dict[str, Any] = field(default_factory=dict)  # middleware scratch

    @property
    def json(self) -> Any:
        if not self.body:
            return None
        try:
            return json.loads(self.body)
        except json.JSONDecodeError:
            raise HttpError(400, "invalid JSON body") from None

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)

    def cookie(self, name: str) -> Optional[str]:
        raw = self.header("cookie")
        if not raw:
            return None
        jar = SimpleCookie()
        jar.load(raw)
        morsel = jar.get(name)
        return morsel.value if morsel else None

    def query1(self, name: str, default: str = "") -> str:
        vals = self.query.get(name)
        return vals[0] if vals else default


def _content_type_of(headers: Dict[str, str]) -> str:
    for k, v in headers.items():
        if k.lower() == "content-type":
            return v
    return "application/json"


@dataclass
class JsonResponse:
    body: Any = None
    status: int = 200
    headers: Dict[str, str] = field(default_factory=dict)
    cookies: List[str] = field(default_factory=list)  # raw Set-Cookie values

    @property
    def content_type(self) -> str:
        return _content_type_of(self.headers)

    def encode(self) -> bytes:
        if self.body is None:
            return b""
        if isinstance(self.body, bytes):
            return self.body
        if isinstance(self.body, str) and not self.content_type.startswith("application/json"):
            return self.body.encode()
        return json.dumps(self.body).encode()


@dataclass
class StreamingResponse:
    """Chunked NDJSON-style response (watch streams). ``chunks`` yields bytes;
    ``on_close`` runs when the stream ends or the client disconnects."""

    chunks: Any  # Iterator[bytes]
    status: int = 200
    headers: Dict[str, str] = field(default_factory=dict)
    on_close: Optional[Callable[[], None]] = None

    @property
    def content_type(self) -> str:
        return _content_type_of(self.headers)


Handler = Callable[[Request], Any]
Middleware = Callable[[Request], Optional[JsonResponse]]


def _compile(pattern: str) -> re.Pattern:
    """``<name>`` params may appear inline (``/v1/models/<name>:predict`` —
    the TF-Serving verb suffix); params match neither ``/`` nor ``:``."""
    parts = re.split(r"(<[a-zA-Z_][a-zA-Z0-9_]*>)", pattern)
    out = []
    for part in parts:
        if part.startswith("<") and part.endswith(">"):
            out.append(f"(?P<{part[1:-1]}>[^/:]+)")
        else:
            out.append(re.escape(part))
    return re.compile("^" + "".join(out) + "/?$")


class App:
    def __init__(self, name: str = "app"):
        self.name = name
        self._routes: List[Tuple[str, str, re.Pattern, Handler]] = []
        self._middleware: List[Middleware] = []

    def route(self, pattern: str, methods: Tuple[str, ...] = ("GET",)) -> Callable[[Handler], Handler]:
        rx = _compile(pattern)

        def deco(fn: Handler) -> Handler:
            for m in methods:
                self._routes.append((m.upper(), pattern, rx, fn))
            return fn

        return deco

    def iter_routes(self):
        """(method, pattern, handler) triples in registration order —
        the source for the generated OpenAPI contract (web/openapi.py)."""
        for method, pattern, _rx, fn in self._routes:
            yield method, pattern, fn

    def middleware(self, fn: Middleware) -> Middleware:
        self._middleware.append(fn)
        return fn

    # -- dispatch ------------------------------------------------------------
    def dispatch(self, req: Request) -> JsonResponse:
        from ..runtime.tracing import TRACER  # late import: web ↛ runtime cycle

        from ..runtime.tracing import format_traceparent

        with TRACER.span(
            f"{self.name} {req.method}",
            traceparent=req.header("traceparent") or None,
            **{"http.method": req.method, "http.target": req.path, "app": self.name},
        ) as span:
            resp = self._dispatch_inner(req)
            span.set("http.status_code", resp.status)
            # echo the handler span back so callers can join client + server
            # timelines without a response-body contract
            resp.headers.setdefault("traceparent", format_traceparent(span))
            if isinstance(resp, StreamingResponse):
                span.set("http.streaming", True)  # span closes at stream start
            elif resp.status >= 500:
                span.status = "ERROR"
                span.status_message = f"HTTP {resp.status}"
            return resp

    def _dispatch_inner(self, req: Request) -> JsonResponse:
        try:
            for mw in self._middleware:
                short = mw(req)
                if short is not None:
                    return short
            for method, _pattern, rx, fn in self._routes:
                if method != req.method:
                    continue
                m = rx.match(req.path)
                if m:
                    req.params = m.groupdict()
                    result = fn(req)
                    if isinstance(result, (JsonResponse, StreamingResponse)):
                        return result
                    return JsonResponse(result)
            if any(rx.match(req.path) for _, _, rx, _ in self._routes):
                raise HttpError(405, f"method {req.method} not allowed")
            raise HttpError(404, f"no route for {req.path}")
        except HttpError as e:
            return JsonResponse({"error": e.message, "status": e.status},
                                status=e.status, headers=dict(e.headers))
        except Exception:
            log.exception("%s: handler error %s %s", self.name, req.method, req.path)
            return JsonResponse({"error": "internal error", "status": 500}, status=500)

    # -- in-process call (tests + service-to-service) ------------------------
    def call(
        self,
        method: str,
        path: str,
        body: Any = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> JsonResponse:
        parsed = urlparse(path)
        raw = b"" if body is None else json.dumps(body).encode()
        req = Request(
            method=method.upper(),
            path=parsed.path,
            query=parse_qs(parsed.query),
            headers={k.lower(): v for k, v in (headers or {}).items()},
            body=raw,
        )
        return self.dispatch(req)

    # -- real server ---------------------------------------------------------
    def serve(self, port: int = 0, host: str = "127.0.0.1", ssl_context=None) -> "AppServer":
        return AppServer(self, host, port, ssl_context=ssl_context)


class AppServer:
    def __init__(self, app: App, host: str, port: int, ssl_context=None):
        self.app = app
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _handle(self) -> None:
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                parsed = urlparse(self.path)
                req = Request(
                    method=self.command,
                    path=parsed.path,
                    query=parse_qs(parsed.query),
                    headers={k.lower(): v for k, v in self.headers.items()},
                    body=body,
                )
                resp = outer.app.dispatch(req)
                if isinstance(resp, StreamingResponse):
                    self._stream(resp)
                    return
                payload = resp.encode()
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in resp.headers.items():
                    if k.lower() != "content-type":  # already sent above
                        self.send_header(k, v)
                for c in resp.cookies:
                    self.send_header("Set-Cookie", c)
                self.end_headers()
                self.wfile.write(payload)

            def _stream(self, resp: StreamingResponse) -> None:
                self.send_response(resp.status)
                self.send_header("Content-Type", resp.content_type)
                self.send_header("Transfer-Encoding", "chunked")
                for k, v in resp.headers.items():
                    if k.lower() != "content-type":
                        self.send_header(k, v)
                self.end_headers()
                try:
                    for chunk in resp.chunks:
                        if not chunk:
                            continue
                        self.wfile.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                        self.wfile.flush()
                    self.wfile.write(b"0\r\n\r\n")
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away — normal watch termination
                finally:
                    if resp.on_close:
                        try:
                            resp.on_close()
                        except Exception:
                            pass

            do_GET = do_POST = do_PUT = do_PATCH = do_DELETE = _handle

        class _Server(ThreadingHTTPServer):
            # an overloaded server must answer 503/504, not RST at the
            # TCP layer: the default socketserver backlog of 5 resets
            # connection bursts before the shedding logic sees them
            request_queue_size = 128

        self.httpd = _Server((host, port), _Handler)
        if ssl_context is not None:
            # Wrap BEFORE the accept thread starts: the port must never
            # serve a plaintext connection on a TLS-configured server.
            self.httpd.socket = ssl_context.wrap_socket(self.httpd.socket, server_side=True)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name=f"{app.name}-http", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
