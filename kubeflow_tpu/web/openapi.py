"""Machine-readable API contracts, generated from the live route table.

The reference publishes a hand-written swagger 2.0 document for KFAM
(components/access-management/api/swagger.yaml) with typed models (Binding,
Profile, Status) and nothing for the CRUD apps. Here every app built on
``web.http.App`` serves a generated contract at ``/apidocs`` (JSON) and
``/apidocs.yaml`` — derived from the actual registered routes so paths can
never drift — and handlers declare their models with ``@annotate``, which
both documents the route and pins it to a named definition the way the
reference's swagger drove its generated typed client
(centraldashboard/app/clients/profile_controller.ts).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from .http import App, JsonResponse, Request

_PARAM_RX = re.compile(r"<([a-zA-Z_][a-zA-Z0-9_]*)>")

# -- shared model definitions (swagger 2.0 `definitions`) --------------------
# One platform-wide vocabulary: apps reference these by name via @annotate;
# only definitions actually referenced by an app's routes are emitted into
# its document (transitively, so $refs always resolve).

DEFINITIONS: Dict[str, Dict[str, Any]] = {
    "Metadata": {
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "namespace": {"type": "string"},
            "uid": {"type": "string"},
            "resourceVersion": {"type": "string"},
            "creationTimestamp": {"type": "string", "format": "date-time"},
            "labels": {"type": "object", "additionalProperties": {"type": "string"}},
            "annotations": {"type": "object", "additionalProperties": {"type": "string"}},
        },
        "required": ["name"],
    },
    "Status": {
        # Mirrors the reference's kfam swagger `Status` / K8s metav1.Status.
        "type": "object",
        "properties": {
            "status": {"type": "string"},
            "message": {"type": "string"},
            "code": {"type": "integer"},
            "resourceVersion": {"type": "string"},
        },
    },
    "Error": {
        "type": "object",
        "properties": {"error": {"type": "string"}},
        "required": ["error"],
    },
    "Subject": {
        "type": "object",
        "properties": {"kind": {"type": "string"}, "name": {"type": "string"}},
        "required": ["name"],
    },
    "RoleRef": {
        "type": "object",
        "properties": {
            "apiGroup": {"type": "string"},
            "kind": {"type": "string"},
            "name": {"type": "string"},
        },
        "required": ["kind", "name"],
    },
    "Binding": {
        # access-management/api/swagger.yaml Binding model, TPU-reshaped.
        "type": "object",
        "properties": {
            "user": {"$ref": "#/definitions/Subject"},
            "referredNamespace": {"type": "string"},
            "roleRef": {"$ref": "#/definitions/RoleRef"},
        },
        "required": ["user", "referredNamespace", "roleRef"],
    },
    "BindingList": {
        "type": "object",
        "properties": {
            "bindings": {"type": "array", "items": {"$ref": "#/definitions/Binding"}}
        },
        "required": ["bindings"],
    },
    "BindingCreated": {
        "type": "object",
        "properties": {
            "status": {"type": "string"},
            "binding": {"type": "object"},
        },
    },
    "Profile": {
        "type": "object",
        "properties": {
            "apiVersion": {"type": "string"},
            "kind": {"type": "string"},
            "metadata": {"$ref": "#/definitions/Metadata"},
            "spec": {
                "type": "object",
                "properties": {
                    "owner": {"$ref": "#/definitions/Subject"},
                    "resourceQuotaSpec": {"type": "object"},
                    "plugins": {"type": "array", "items": {"type": "object"}},
                },
            },
            "status": {"type": "object"},
        },
    },
    "TpuSpec": {
        "type": "object",
        "properties": {
            "generation": {"type": "string"},
            "topology": {"type": "string"},
            "numHosts": {"type": "integer"},
            "chips": {"type": "integer"},
        },
    },
    "NotebookSummary": {
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "namespace": {"type": "string"},
            "image": {"type": "string"},
            "tpu": {"$ref": "#/definitions/TpuSpec"},
            "status": {"$ref": "#/definitions/UiStatus"},
            "serverType": {"type": "string"},
        },
        "required": ["name", "namespace", "status"],
    },
    "UiStatus": {
        "type": "object",
        "properties": {"phase": {"type": "string"}, "message": {"type": "string"}},
        "required": ["phase"],
    },
    "NotebookList": {
        "type": "object",
        "properties": {
            "notebooks": {
                "type": "array",
                "items": {"$ref": "#/definitions/NotebookSummary"},
            }
        },
        "required": ["notebooks"],
    },
    "TpuInfo": {
        "type": "object",
        "properties": {
            "generation": {"type": "string"},
            "topologies": {"type": "array", "items": {"type": "string"}},
            "chipsPerNode": {"type": "integer"},
        },
        "required": ["generation", "topologies"],
    },
    "TpuList": {
        "type": "object",
        "properties": {
            "tpus": {"type": "array", "items": {"$ref": "#/definitions/TpuInfo"}}
        },
        "required": ["tpus"],
    },
    "PodDefaultInfo": {
        "type": "object",
        "properties": {
            "label": {"type": "string"},
            "desc": {"type": "string"},
            "name": {"type": "string"},
        },
        "required": ["name"],
    },
    "PodDefaultList": {
        "type": "object",
        "properties": {
            "poddefaults": {
                "type": "array",
                "items": {"$ref": "#/definitions/PodDefaultInfo"},
            }
        },
        "required": ["poddefaults"],
    },
    "TensorboardList": {
        "type": "object",
        "properties": {
            "tensorboards": {"type": "array", "items": {"type": "object"}}
        },
        "required": ["tensorboards"],
    },
    "PvcList": {
        "type": "object",
        "properties": {"pvcs": {"type": "array", "items": {"type": "object"}}},
        "required": ["pvcs"],
    },
    "SpawnForm": {
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "image": {"type": "string"},
            "cpu": {"type": "string"},
            "memory": {"type": "string"},
            "tpus": {"type": "object"},
            "workspaceVolume": {"type": "object"},
            "dataVolumes": {"type": "array", "items": {"type": "object"}},
            "configurations": {"type": "array", "items": {"type": "string"}},
            "affinityConfig": {"type": "string"},
            "tolerationGroup": {"type": "string"},
            "shm": {"type": "boolean"},
        },
        "required": ["name"],
    },
    "EnvInfo": {
        "type": "object",
        "properties": {
            "user": {"type": "string"},
            "platform": {"type": "object"},
            "namespaces": {"type": "array", "items": {"type": "object"}},
            "isClusterAdmin": {"type": "boolean"},
        },
    },
    "WorkgroupExists": {
        "type": "object",
        "properties": {
            "hasWorkgroup": {"type": "boolean"},
            "user": {"type": "string"},
            "namespaces": {"type": "array", "items": {"type": "string"}},
            "hasAuth": {"type": "boolean"},
            "registrationFlowAllowed": {"type": "boolean"},
        },
        "required": ["hasWorkgroup", "user"],
    },
}

_REF_RX = re.compile(r"#/definitions/([A-Za-z0-9_]+)")


def annotate(
    response: Optional[str] = None,
    request: Optional[str] = None,
    query: Optional[List[Dict[str, Any]]] = None,
):
    """Attach swagger model names to a handler: ``response``/``request`` are
    keys into DEFINITIONS; ``query`` is a list of swagger query-parameter
    dicts. Used by openapi_document to emit typed per-route schemas."""

    def deco(fn):
        fn.__openapi__ = {"response": response, "request": request, "query": query}
        return fn

    return deco


def _collect_refs(schema: Any, out: set) -> None:
    if isinstance(schema, dict):
        for v in schema.values():
            _collect_refs(v, out)
    elif isinstance(schema, list):
        for v in schema:
            _collect_refs(v, out)
    elif isinstance(schema, str):
        for name in _REF_RX.findall(schema):
            out.add(name)


def _swagger_path(pattern: str) -> str:
    return _PARAM_RX.sub(r"{\1}", pattern)


def openapi_document(app: App, base_path: str = "/", version: str = "1.0") -> Dict[str, Any]:
    """Swagger 2.0 document from the app's route table.

    Handler docstrings (first line) become operation summaries; ``@annotate``
    marks become typed request/response schemas referencing `definitions`
    (emitted transitively so every $ref resolves).
    """
    paths: Dict[str, Dict[str, Any]] = {}
    used: set = set()
    for method, pattern, fn in app.iter_routes():
        swagger = _swagger_path(pattern)
        params: List[Dict[str, Any]] = [
            {"name": name, "in": "path", "required": True, "type": "string"}
            for name in _PARAM_RX.findall(pattern)
        ]
        marks = getattr(fn, "__openapi__", {})
        op: Dict[str, Any] = {
            "operationId": f"{fn.__name__}_{method.lower()}",
            "responses": {"200": {"description": "OK"}},
        }
        if marks.get("response"):
            ref = f"#/definitions/{marks['response']}"
            op["responses"]["200"]["schema"] = {"$ref": ref}
            used.add(marks["response"])
        doc = (fn.__doc__ or "").strip().splitlines()
        if doc:
            op["summary"] = doc[0].strip()
        for qp in marks.get("query") or []:
            params.append({"in": "query", "type": "string", **qp})
        if method in ("POST", "PUT", "PATCH", "DELETE") and (
            marks.get("request") or method != "DELETE"
        ):
            body_schema: Dict[str, Any] = {"type": "object"}
            if marks.get("request"):
                body_schema = {"$ref": f"#/definitions/{marks['request']}"}
                used.add(marks["request"])
            params.append({"name": "body", "in": "body", "schema": body_schema})
            op["consumes"] = ["application/json"]
        if params:
            op["parameters"] = params
        paths.setdefault(swagger, {})[method.lower()] = op

    # Transitive closure so nested $refs (Binding → Subject) resolve.
    frontier = set(used)
    while frontier:
        nxt: set = set()
        for name in frontier:
            _collect_refs(DEFINITIONS.get(name, {}), nxt)
        frontier = nxt - used
        used |= nxt
    definitions = {n: DEFINITIONS[n] for n in sorted(used) if n in DEFINITIONS}

    doc: Dict[str, Any] = {
        "swagger": "2.0",
        "info": {"title": app.name, "version": version},
        "basePath": base_path,
        "schemes": ["http", "https"],
        "produces": ["application/json"],
        "paths": dict(sorted(paths.items())),
    }
    if definitions:
        doc["definitions"] = definitions
    return doc


def install_apidocs(app: App, base_path: str = "/", version: str = "1.0") -> None:
    """Serve the generated contract at /apidocs + /apidocs.yaml.

    Registered LAST so the document covers every route added before it;
    the /apidocs routes themselves are excluded.
    """

    @app.route("/apidocs")
    def apidocs(req: Request):
        return _document_cached()

    @app.route("/apidocs.yaml")
    def apidocs_yaml(req: Request):
        import yaml

        text = yaml.safe_dump(_document_cached(), sort_keys=False)
        return JsonResponse(text, headers={"Content-Type": "application/yaml"})

    _skip = {"apidocs", "apidocs_yaml"}
    _cache: Dict[str, Any] = {}

    def _document_cached() -> Dict[str, Any]:
        if not _cache:
            doc = openapi_document(app, base_path=base_path, version=version)
            for path in ("/apidocs", "/apidocs.yaml"):
                doc["paths"].pop(path, None)
            _cache.update(doc)
        return _cache
