"""Machine-readable API contracts, generated from the live route table.

The reference publishes a hand-written swagger 2.0 document for KFAM
(components/access-management/api/swagger.yaml) and nothing for the CRUD
apps. Here every app built on ``web.http.App`` can serve a generated
contract at ``/apidocs`` (JSON) and ``/apidocs.yaml`` — derived from the
actual registered routes, so it can never drift from the implementation.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List

from .http import App, JsonResponse, Request

_PARAM_RX = re.compile(r"<([a-zA-Z_][a-zA-Z0-9_]*)>")


def _swagger_path(pattern: str) -> str:
    return _PARAM_RX.sub(r"{\1}", pattern)


def openapi_document(app: App, base_path: str = "/", version: str = "1.0") -> Dict[str, Any]:
    """Swagger 2.0 document from the app's route table.

    Handler docstrings (first line) become operation summaries.
    """
    paths: Dict[str, Dict[str, Any]] = {}
    for method, pattern, fn in app.iter_routes():
        swagger = _swagger_path(pattern)
        params: List[Dict[str, Any]] = [
            {"name": name, "in": "path", "required": True, "type": "string"}
            for name in _PARAM_RX.findall(pattern)
        ]
        op: Dict[str, Any] = {
            "operationId": f"{fn.__name__}_{method.lower()}",
            "responses": {"200": {"description": "OK"}},
        }
        doc = (fn.__doc__ or "").strip().splitlines()
        if doc:
            op["summary"] = doc[0].strip()
        if params:
            op["parameters"] = params
        if method in ("POST", "PUT", "PATCH"):
            op.setdefault("parameters", []).append(
                {"name": "body", "in": "body", "schema": {"type": "object"}}
            )
            op["consumes"] = ["application/json"]
        paths.setdefault(swagger, {})[method.lower()] = op
    return {
        "swagger": "2.0",
        "info": {"title": app.name, "version": version},
        "basePath": base_path,
        "schemes": ["http", "https"],
        "produces": ["application/json"],
        "paths": dict(sorted(paths.items())),
    }


def install_apidocs(app: App, base_path: str = "/", version: str = "1.0") -> None:
    """Serve the generated contract at /apidocs + /apidocs.yaml.

    Registered LAST so the document covers every route added before it;
    the /apidocs routes themselves are excluded.
    """

    @app.route("/apidocs")
    def apidocs(req: Request):
        return _document_cached()

    @app.route("/apidocs.yaml")
    def apidocs_yaml(req: Request):
        import yaml

        text = yaml.safe_dump(_document_cached(), sort_keys=False)
        return JsonResponse(text, headers={"Content-Type": "application/yaml"})

    _skip = {"apidocs", "apidocs_yaml"}
    _cache: Dict[str, Any] = {}

    def _document_cached() -> Dict[str, Any]:
        if not _cache:
            doc = openapi_document(app, base_path=base_path, version=version)
            for path in ("/apidocs", "/apidocs.yaml"):
                doc["paths"].pop(path, None)
            _cache.update(doc)
        return _cache
