"""AuthN/AuthZ/CSRF middleware: the crud_backend cross-cutting plane.

Mirrors the reference's shared Flask backend
(crud-web-apps/common/backend/kubeflow/kubeflow/crud_backend/):

- identity from a trusted proxy header (``authn.py``: env ``USERID_HEADER``
  default ``kubeflow-userid``, optional prefix strip),
- per-call authorization (``authz.py`` SubjectAccessReview) — here resolved
  in-process against RoleBindings/ClusterRoleBindings in the store, with
  the kubeflow-admin/edit/view ClusterRole verb model,
- CSRF double-submit cookie (``csrf.py``: XSRF-TOKEN cookie must equal the
  X-XSRF-TOKEN header on unsafe methods),
- health probes bypass (``probes.py``).
"""

from __future__ import annotations

import hmac
import secrets
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from ..apiserver.client import Client
from .http import App, HttpError, JsonResponse, Request

USERID_HEADER = "kubeflow-userid"
XSRF_COOKIE = "XSRF-TOKEN"
XSRF_HEADER = "x-xsrf-token"
UNSAFE = {"POST", "PUT", "PATCH", "DELETE"}

#: verb sets per platform ClusterRole (reference kfam bindings.go:39-46 role
#: model + kubeflow-edit/view RBAC manifests).
ROLE_VERBS: Dict[str, Set[str]] = {
    "kubeflow-admin": {"get", "list", "watch", "create", "update", "patch", "delete"},
    "kubeflow-edit": {"get", "list", "watch", "create", "update", "patch", "delete"},
    "kubeflow-view": {"get", "list", "watch"},
}


GATEWAY_TOKEN_HEADER = "x-gateway-token"


@dataclass
class AuthConfig:
    userid_header: str = USERID_HEADER
    userid_prefix: str = ""
    disable_auth: bool = False  # APP_DISABLE_AUTH analog (dev mode)
    default_user: str = "anonymous@kubeflow.org"
    cluster_admins: List[str] = field(default_factory=list)
    secure_cookies: bool = False
    # Trust root for the identity header (VERDICT r4 missing #2): when set
    # (GATEWAY_SHARED_SECRET env), ONLY requests carrying the front
    # gateway's x-gateway-token may assert kubeflow-userid — a direct-to-
    # backend request with a hand-written identity header is rejected, the
    # Istio per-request-enforcement analog (services/gateway.py).
    gateway_secret: str = ""


def user_of(req: Request, cfg: AuthConfig) -> str:
    raw = req.header(cfg.userid_header)
    if not raw:
        if cfg.disable_auth:
            return cfg.default_user
        raise HttpError(401, f"missing identity header {cfg.userid_header!r}")
    if cfg.gateway_secret and not hmac.compare_digest(
            req.header(GATEWAY_TOKEN_HEADER), cfg.gateway_secret):
        raise HttpError(
            401, "identity header not asserted by the trusted gateway")
    if cfg.userid_prefix and raw.startswith(cfg.userid_prefix):
        raw = raw[len(cfg.userid_prefix):]
    return raw


class Authorizer:
    """In-process SubjectAccessReview over store RBAC objects."""

    def __init__(self, client: Client, cfg: Optional[AuthConfig] = None):
        self.client = client
        self.cfg = cfg or AuthConfig()

    def is_cluster_admin(self, user: str) -> bool:
        if user in self.cfg.cluster_admins:
            return True
        for crb in self.client.list("rbac.authorization.k8s.io/v1", "ClusterRoleBinding"):
            if (crb.get("roleRef") or {}).get("name") not in ("cluster-admin", "kubeflow-admin"):
                continue
            for sub in crb.get("subjects") or []:
                if sub.get("kind") == "User" and sub.get("name") == user:
                    return True
        return False

    def is_authorized(self, user: str, verb: str, namespace: Optional[str]) -> bool:
        if self.cfg.disable_auth or self.is_cluster_admin(user):
            return True
        if namespace is None:
            return verb in ("get", "list", "watch")
        for rb in self.client.list("rbac.authorization.k8s.io/v1", "RoleBinding", namespace):
            role = (rb.get("roleRef") or {}).get("name", "")
            verbs = ROLE_VERBS.get(role)
            if not verbs or verb not in verbs:
                continue
            for sub in rb.get("subjects") or []:
                if sub.get("kind", "User") == "User" and sub.get("name") == user:
                    return True
        return False

    def ensure(self, user: str, verb: str, namespace: Optional[str]) -> None:
        if not self.is_authorized(user, verb, namespace):
            raise HttpError(
                403, f"user {user!r} is not allowed to {verb} in namespace {namespace!r}"
            )


def install_auth(
    app: App,
    authorizer: Authorizer,
    enable_csrf: bool = True,
    readiness_check: Optional[Callable[[], None]] = None,
) -> None:
    """Probes bypass + identity (+ CSRF for browser-facing apps), in order.

    Server-to-server APIs (KFAM — the dashboard BFF calls it with the user's
    forwarded identity header) skip CSRF, as the reference does: csrf.py
    lives only in the crud_backend the browser talks to.

    Probe split (reference crud_backend/probes.py:7-16): ``/healthz/liveness``
    answers whenever the process serves requests; ``/healthz/readiness`` runs
    ``readiness_check`` (default: one apiserver list round-trip) and returns
    503 on failure, so manifests can distinguish "up" from "ready". Bare
    ``/healthz`` stays as the liveness alias."""
    cfg = authorizer.cfg
    if readiness_check is None:
        def readiness_check() -> None:  # default: backing apiserver reachable
            authorizer.client.list("v1", "Namespace")

    @app.middleware
    def probes(req: Request) -> Optional[JsonResponse]:
        if req.path in ("/healthz", "/healthz/liveness"):
            return JsonResponse({"status": "ok"})
        if req.path == "/healthz/readiness":
            try:
                readiness_check()
            except Exception as e:
                return JsonResponse({"status": "unready", "reason": str(e)}, status=503)
            return JsonResponse({"status": "ok"})
        return None

    @app.middleware
    def authn(req: Request) -> Optional[JsonResponse]:
        req.context["user"] = user_of(req, cfg)
        return None

    @app.middleware
    def csrf(req: Request) -> Optional[JsonResponse]:
        if not enable_csrf or req.method not in UNSAFE:
            return None
        cookie = req.cookie(XSRF_COOKIE)
        header = req.header(XSRF_HEADER)
        if cfg.disable_auth and not cookie and not header:
            return None  # dev mode without a browser session
        if not cookie or not header or not hmac.compare_digest(cookie, header):
            raise HttpError(403, "CSRF token missing or mismatched")
        return None


def issue_csrf_cookie(resp: JsonResponse, cfg: AuthConfig) -> str:
    token = secrets.token_urlsafe(32)
    attrs = f"{XSRF_COOKIE}={token}; Path=/; SameSite=Strict"
    if cfg.secure_cookies:
        attrs += "; Secure"
    resp.cookies.append(attrs)
    return token
