"""Shared secondary-resource endpoints for the CRUD web apps.

The reference's shared Flask backend exposes more than each app's primary
kind: secrets, storage classes, nodes, pods and generic custom resources
(crud-web-apps/common/backend/kubeflow/kubeflow/crud_backend/api/
{secret,storageclass,node,pod,custom_resource}.py) — the volumes form
consumes storage classes, the spawner shows node capacity, config panels
list secrets. ``install_cluster_api`` adds the same surface to any app built
on ``web.http.App``, with the platform's per-call authorization.
"""

from __future__ import annotations


from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import ApiError, Conflict, NotFound
from .auth import Authorizer
from .http import App, HttpError, Request


def install_cluster_api(app: App, client: Client, authorizer: Authorizer,
                        cache=None) -> None:
    # Shell selector reads through the app's shared informer when it has one
    # (every SPA load hits this); a cache-less app falls back to live lists.
    reader = cache if cache is not None else client

    @app.route("/api/namespaces")
    def list_namespaces(req: Request):
        """List namespaces (shell namespace selector; reference
        crud_backend api/namespace.py)."""
        return [apimeta.name_of(n) for n in reader.list("v1", "Namespace")]

    @app.route("/api/storageclasses")
    def list_storageclasses(req: Request):
        """List StorageClasses (volumes form storage-class picker)."""
        # Cluster-scoped read: any authenticated user may list, like the
        # reference's storageclass.py (it runs with the backend's own SA).
        return {
            "storageClasses": [
                {
                    "name": apimeta.name_of(sc),
                    "provisioner": sc.get("provisioner", ""),
                    "isDefault": (apimeta.annotations_of(sc).get(
                        "storageclass.kubernetes.io/is-default-class") == "true"),
                }
                for sc in client.list("storage.k8s.io/v1", "StorageClass")
            ]
        }

    @app.route("/api/nodes")
    def list_nodes(req: Request):
        """List nodes with capacity (TPU/accelerator discovery)."""
        return {
            "nodes": [
                {
                    "name": apimeta.name_of(n),
                    "labels": apimeta.labels_of(n),
                    "capacity": n.get("status", {}).get("capacity", {}),
                    "allocatable": n.get("status", {}).get("allocatable", {}),
                }
                for n in client.list("v1", "Node")
            ]
        }

    @app.route("/api/namespaces/<ns>/secrets")
    def list_secrets(req: Request):
        """List secret names/types in a namespace (values never leave the server)."""
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "list", ns)
        return {
            "secrets": [
                {
                    "name": apimeta.name_of(s),
                    "type": s.get("type", "Opaque"),
                    "keys": sorted((s.get("data") or {}).keys()),
                }
                for s in client.list("v1", "Secret", ns)
            ]
        }

    @app.route("/api/namespaces/<ns>/pods")
    def list_pods(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "list", ns)
        return {
            "pods": [
                {
                    "name": apimeta.name_of(p),
                    "phase": p.get("status", {}).get("phase", ""),
                    "labels": apimeta.labels_of(p),
                }
                for p in client.list("v1", "Pod", ns)
            ]
        }

    # -- generic custom-resource CRUD (custom_resource.py:1-34) ---------------
    # apiVersion is split across two path segments (group contains no "/").
    def _cr(req: Request):
        group, version = req.params["group"], req.params["version"]
        return f"{group}/{version}", req.params["kind"]

    @app.route("/api/namespaces/<ns>/customresources/<group>/<version>/<kind>")
    def list_custom(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "list", ns)
        api, kind = _cr(req)
        try:
            return {"items": client.list(api, kind, ns)}
        except ApiError as e:
            raise HttpError(400, str(e)) from None

    @app.route("/api/namespaces/<ns>/customresources/<group>/<version>/<kind>/<name>")
    def get_custom(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "get", ns)
        api, kind = _cr(req)
        try:
            return client.get(api, kind, req.params["name"], ns)
        except NotFound:
            raise HttpError(404, f"{kind} {req.params['name']!r} not found") from None

    @app.route("/api/namespaces/<ns>/customresources/<group>/<version>/<kind>", methods=("POST",))
    def create_custom(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "create", ns)
        api, kind = _cr(req)
        body = req.json
        if not isinstance(body, dict):
            raise HttpError(400, "object body required")
        obj = dict(body)
        obj.setdefault("apiVersion", api)
        obj.setdefault("kind", kind)
        if obj["apiVersion"] != api or obj["kind"] != kind:
            raise HttpError(400, "body apiVersion/kind must match the path")
        obj.setdefault("metadata", {}).setdefault("namespace", ns)
        if obj["metadata"]["namespace"] != ns:
            raise HttpError(400, "body namespace must match the path")
        try:
            return {"status": "created", "object": client.create(obj)}
        except Conflict:
            name = obj["metadata"].get("name", "?")
            raise HttpError(409, f"{kind} {name!r} exists") from None
        except ApiError as e:
            raise HttpError(400, str(e)) from None

    @app.route(
        "/api/namespaces/<ns>/customresources/<group>/<version>/<kind>/<name>",
        methods=("DELETE",),
    )
    def delete_custom(req: Request):
        ns = req.params["ns"]
        authorizer.ensure(req.context["user"], "delete", ns)
        api, kind = _cr(req)
        try:
            client.delete(api, kind, req.params["name"], ns)
        except NotFound:
            raise HttpError(404, f"{kind} {req.params['name']!r} not found") from None
        return {"status": "deleted"}
