"""Minimal HTTP service layer for the platform's REST planes.

The reference uses Express (centraldashboard), Flask (crud-web-apps), and
net/http (KFAM). This image ships none of those; the platform instead has
one small stdlib-only router shared by every service — KFAM, the spawner
backends, the dashboard BFF — with the reference's cross-cutting concerns
(identity header parsing, SubjectAccessReview-style authz, CSRF
double-submit, probes) as middleware in kubeflow_tpu.web.auth.
"""

from kubeflow_tpu.web.http import App, HttpError, JsonResponse, Request  # noqa: F401
