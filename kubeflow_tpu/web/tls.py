"""TLS plumbing for the platform's HTTP boundaries.

The substrate the reference builds on is TLS-only (the Kubernetes API
server), and the reference webhook refuses to start without certs
(admission-webhook/main.go:595-596, certs at /etc/webhook/certs). This
module gives every role the same three pieces:

- :func:`server_context` / :func:`client_context` — ssl.SSLContext
  construction from PEM files (server: cert+key; client: a CA bundle to
  verify the apiserver's cert against).
- :func:`generate_self_signed` — a dev/e2e CA-less self-signed cert with
  the SANs the in-cluster service DNS uses, so the five-process e2e and
  unit tests exercise the real TLS handshake without external tooling.
  Production deployments mount real certs (manifests/apiserver).

Env contract (consumed by apiserver/__main__.py and RemoteStore):
``APISERVER_TLS_CERT_FILE``/``APISERVER_TLS_KEY_FILE`` enable HTTPS on the
apiserver; ``APISERVER_CA_FILE`` is the bundle clients verify with.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
import ssl
from typing import Optional, Sequence, Tuple

#: SANs every generated cert carries — the names clients dial in-cluster
#: (service DNS, short forms) and in tests (loopback).
DEFAULT_SANS = (
    "localhost",
    "apiserver",
    "apiserver.kubeflow",
    "apiserver.kubeflow.svc",
    "apiserver.kubeflow.svc.cluster.local",
)


def server_context(cert_file: str, key_file: str) -> ssl.SSLContext:
    """TLS server context; certs load (and fail) before any socket binds."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_file, key_file)
    return ctx


def client_context(ca_file: Optional[str] = None, ca_data: Optional[str] = None) -> ssl.SSLContext:
    """Verifying client context. ``ca_file`` (a path) or ``ca_data`` (the
    PEM itself — the kubeconfig ``certificate-authority-data`` pattern, so
    manifests can inject the bundle from a Secret key without a volume
    mount) is REQUIRED to trust a private cert — verification is never
    disabled; a client that cannot verify must fail the handshake, not
    silently trust."""
    return ssl.create_default_context(cafile=ca_file or None, cadata=ca_data or None)


def generate_self_signed(
    directory: str,
    common_name: str = "apiserver",
    sans: Sequence[str] = DEFAULT_SANS,
    days: int = 7,
) -> Tuple[str, str]:
    """Write ``tls.crt``/``tls.key`` under ``directory`` and return their
    paths. Key is 2048-bit RSA; SANs cover DEFAULT_SANS + 127.0.0.1 so the
    same cert verifies for loopback tests and in-cluster DNS."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    alt_names = [x509.DNSName(s) for s in sans]
    alt_names.append(x509.IPAddress(ipaddress.ip_address("127.0.0.1")))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=days))
        .add_extension(x509.SubjectAlternativeName(alt_names), critical=False)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .sign(key, hashes.SHA256())
    )
    cert_path = os.path.join(directory, "tls.crt")
    key_path = os.path.join(directory, "tls.key")
    with open(cert_path, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(key_path, "wb") as f:
        f.write(
            key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.TraditionalOpenSSL,
                serialization.NoEncryption(),
            )
        )
    os.chmod(key_path, 0o600)
    return cert_path, key_path
