// kfui — declarative hypermedia runtime for the platform SPAs.
//
// The kubeflow-common-lib analog (reference: crud-web-apps/common/frontend/
// kubeflow-common-lib — resource-table, namespace-select, polling with
// exponential backoff, confirm-dialog, snack-bar, status icons; and
// centraldashboard/public/components — cards, charts, manage-users,
// registration). Re-designed for air-gapped TPU pods: no npm toolchain, no
// framework — pages declare components and flows with data-kf-* attributes
// and this ~single-file runtime interprets them. The SAME attributes are
// interpreted by the Python DOM harness (e2e/uidom.py), so every UI flow is
// exercised end-to-end in CI without a browser, and here in one.
//
// Attribute vocabulary (all templates may use {path.to.field} against the
// active context: page ns, fetched item, or table row):
//
//   data-kf-table="/api/...{ns}.../notebooks"   resource table
//     data-kf-items="notebooks"                 JSON key of the row array
//     data-kf-poll="3000"                       poll interval ms (w/ backoff)
//     data-kf-empty="no notebooks"              empty-state text
//     + child <template data-kf-row> holding one <tr> with {placeholders}
//   data-kf-action="POST:/api/...{name}"        button-triggered call
//     data-kf-body='{"stopped": true}'          JSON body template
//     data-kf-confirm="Delete {name}?"          confirm dialog first
//     data-kf-then="refresh:#tbl"               refresh:<sel> | reload | none
//   data-kf-form="POST:/api/namespaces/{ns}/notebooks"  submit → JSON body
//     (field names become JSON keys; dots nest: tpus.generation, numeric
//      segments index arrays: dataVolumes.0.name;
//      data-kf-omit-if="none" drops the field when it holds that value)
//   data-kf-options="/api/tpus;tpus;generation;{generation}"  select options
//     data-kf-keep-first                        keep the static first <option>
//   data-kf-depends="#f-gen"                    re-derive options on change:
//     data-kf-options="/api/tpus;tpus[generation={dep}].topologies;.;{.}"
//   data-kf-text="/api/workgroup/exists;user"   fetch → textContent
//   data-kf-show-if="/api/workgroup/exists;hasWorkgroup;false"  conditional
//   data-kf-chart="/api/metrics/node;.;node;utilization"  SVG bar chart
//   data-kf-ns-select                           namespace picker (?ns=)
//   data-kf-nav="/jupyter/"                     nav links carrying ?ns=
//
// Exponential backoff matches the reference's polling/exponential-backoff.ts:
// interval doubles per consecutive failure up to maxInterval, resets on
// success.
"use strict";

(function () {
  const kf = (window.kfui = {});

  // ---- context + templating ------------------------------------------------
  kf.ns = function () {
    return new URLSearchParams(location.search).get("ns") || "kubeflow-user";
  };

  function lookup(obj, path) {
    if (path === "." || path === "") return obj;
    let cur = obj;
    for (const part of path.split(".")) {
      if (cur == null) return undefined;
      cur = cur[part];
    }
    return cur;
  }

  // Placeholders are identifier-shaped ({.}, {ns}, {status.phase}) so JSON
  // body templates ({"stopped": true}) pass through untouched.
  function substWith(template, ctx, encode) {
    return String(template).replace(/\{(\.|[A-Za-z_$][\w$.]*)\}/g, (_, path) => {
      let v;
      if (path === "ns") v = kf.ns();
      else v = path === "." ? ctx : lookup(ctx, path);
      if (v === undefined || v === null) v = "";
      return encode ? encode(String(v)) : String(v);
    });
  }
  function subst(template, ctx) { return substWith(template, ctx, null); }
  // For values substituted INSIDE a JSON body template: escape so quotes and
  // backslashes in data (e.g. a contributor name) can't break JSON.parse.
  function substJson(template, ctx) {
    return substWith(template, ctx, (s) => JSON.stringify(s).slice(1, -1));
  }
  kf.subst = subst;

  // items path with one-level filter: "tpus[generation=v5e].topologies"
  function itemsAt(data, path, ctx) {
    if (!path || path === ".") return Array.isArray(data) ? data : [];
    let cur = data;
    for (const seg of path.split(".")) {
      if (cur == null) return [];
      const m = seg.match(/^([^[]*)(?:\[([^=\]]+)=([^\]]*)\])?$/);
      if (m[1]) cur = lookup(cur, m[1]);
      if (m[2] !== undefined && Array.isArray(cur)) {
        const want = subst(m[3], ctx);
        cur = cur.find((it) => String(lookup(it, m[2])) === want);
      }
    }
    return cur == null ? [] : Array.isArray(cur) ? cur : [cur];
  }
  kf.itemsAt = itemsAt;

  // ---- transport (CSRF double-submit, JSON, error surfacing) ---------------
  function cookie(name) {
    const m = document.cookie.match(new RegExp("(?:^|; )" + name + "=([^;]*)"));
    return m ? decodeURIComponent(m[1]) : null;
  }

  kf.api = async function (method, path, body) {
    // During kf.init several components often bind the same endpoint
    // (e.g. /api/workgroup/exists drives the user label AND both
    // conditional views): memoize GETs for the init pass only. Pollers
    // and actions run after init and always fetch fresh.
    if (method === "GET" && kf._initMemo) {
      if (!(path in kf._initMemo)) kf._initMemo[path] = kf._fetch(method, path, body);
      return kf._initMemo[path];
    }
    return kf._fetch(method, path, body);
  };

  kf._fetch = async function (method, path, body) {
    const headers = { "content-type": "application/json" };
    const token = cookie("XSRF-TOKEN");
    if (token) headers["x-xsrf-token"] = token;
    const resp = await fetch(path, {
      method,
      headers,
      credentials: "same-origin",
      body: body === undefined ? undefined : JSON.stringify(body),
    });
    const text = await resp.text();
    let data = null;
    try { data = text ? JSON.parse(text) : null; } catch (e) { data = text; }
    if (!resp.ok) {
      throw new Error((data && data.error) || resp.statusText || "request failed");
    }
    return data;
  };

  // ---- snack bar -----------------------------------------------------------
  kf.snack = function (message, kind) {
    let bar = document.getElementById("kf-snack");
    if (!bar) {
      bar = document.createElement("div");
      bar.id = "kf-snack";
      document.body.append(bar);
    }
    bar.textContent = message;
    bar.className = "show " + (kind || "info");
    clearTimeout(bar._t);
    bar._t = setTimeout(() => (bar.className = ""), kf.DEFAULTS.snack_ms);
  };

  // ---- confirm dialog ------------------------------------------------------
  kf.confirm = function (message) {
    return new Promise((resolve) => {
      let dlg = document.getElementById("kf-confirm");
      if (!dlg) {
        dlg = document.createElement("dialog");
        dlg.id = "kf-confirm";
        dlg.innerHTML =
          '<p id="kf-confirm-msg"></p><div class="row">' +
          '<button id="kf-confirm-no" class="ghost">Cancel</button>' +
          '<button id="kf-confirm-yes" class="danger">Confirm</button></div>';
        document.body.append(dlg);
      }
      dlg.querySelector("#kf-confirm-msg").textContent = message;
      dlg.querySelector("#kf-confirm-yes").onclick = () => { dlg.close(); resolve(true); };
      dlg.querySelector("#kf-confirm-no").onclick = () => { dlg.close(); resolve(false); };
      dlg.showModal();
    });
  };

  // ---- exponential backoff poller (exponential-backoff.ts semantics) -------
  kf.poller = function (fn, interval, maxInterval) {
    const base = interval || kf.DEFAULTS.poll_ms;
    const max = maxInterval || kf.DEFAULTS.poll_max_ms;
    let cur = base;
    let timer = null;
    let stopped = false;
    async function tick() {
      try {
        await fn();
        cur = base; // success resets the backoff
      } catch (e) {
        cur = Math.min(cur * 2, max); // failure doubles it
      }
      if (!stopped) timer = setTimeout(tick, cur);
    }
    tick();
    return {
      stop() { stopped = true; clearTimeout(timer); },
      get interval() { return cur; },
    };
  };

  // ---- component: resource table -------------------------------------------
  function initTable(node) {
    const url = node.getAttribute("data-kf-table");
    const itemsPath = node.getAttribute("data-kf-items") || kf.DEFAULTS.items_path;
    const pollMs = parseInt(node.getAttribute("data-kf-poll") || "0", 10);
    const pageSize = parseInt(node.getAttribute("data-kf-page-size") || "0", 10);
    // explicit data-kf-empty="" means "render nothing", only absence defaults
    const emptyText = node.hasAttribute("data-kf-empty")
      ? node.getAttribute("data-kf-empty") : kf.DEFAULTS.empty_text;
    const template = node.querySelector("template[data-kf-row]");
    const tbody = node.querySelector("tbody") || node;
    node._kfPage = 0;

    // th[data-kf-sort="<path>"]: click toggles asc/desc on that item path;
    // numeric when every key parses as a number, else locale string order.
    function sortRows(rows) {
      const s = node._kfSort;
      if (!s) return rows;
      const keyed = rows.map((r) => {
        const v = lookup(r, s.path);
        return [v === null || v === undefined ? "" : v, r];
      });
      const numeric = keyed.every(([v]) => v === "" || !isNaN(Number(v)));
      keyed.sort(([a], [b]) => {
        const cmp = numeric ? Number(a || 0) - Number(b || 0)
                            : String(a).localeCompare(String(b));
        return s.dir === "desc" ? -cmp : cmp;
      });
      return keyed.map(([, r]) => r);
    }

    // [data-kf-pager] child (usually a tfoot cell) gets prev/label/next
    function renderPager(total, pages) {
      const pager = node.querySelector("[data-kf-pager]");
      if (!pager) return;
      pager.replaceChildren();
      const prev = document.createElement("button");
      prev.type = "button";
      prev.className = "kf-page-prev";
      prev.textContent = "‹";
      prev.disabled = node._kfPage <= 0;
      prev.onclick = () => { node._kfPage -= 1; render(node._kfLast); };
      const label = document.createElement("span");
      label.className = "kf-page-label";
      label.textContent = (pages ? node._kfPage + 1 : 0) + "/" + pages + " (" + total + ")";
      const next = document.createElement("button");
      next.type = "button";
      next.className = "kf-page-next";
      next.textContent = "›";
      next.disabled = node._kfPage >= pages - 1;
      next.onclick = () => { node._kfPage += 1; render(node._kfLast); };
      pager.append(prev, label, next);
    }

    function render(data) {
      node._kfLast = data;
      let rows = sortRows(itemsAt(data, itemsPath, {}).slice());
      const total = rows.length;
      if (pageSize > 0) {
        const pages = Math.max(1, Math.ceil(total / pageSize));
        node._kfPage = Math.max(0, Math.min(node._kfPage, pages - 1));
        rows = rows.slice(node._kfPage * pageSize, (node._kfPage + 1) * pageSize);
        renderPager(total, pages);
      }
      tbody.replaceChildren();
      if (!rows.length) {
        const tr = document.createElement("tr");
        const td = document.createElement("td");
        td.className = "empty";
        td.colSpan = (node.querySelectorAll("thead th") || []).length || 1;
        td.textContent = emptyText;
        tr.append(td);
        tbody.append(tr);
        return;
      }
      for (const row of rows) {
        const frag = template.content.cloneNode(true);
        materialize(frag, row);
        tbody.append(frag);
      }
    }
    async function refresh() {
      render(await kf.api("GET", subst(url, {})));
    }
    node._kfRender = render;
    node._kfRefresh = refresh;
    for (const th of node.querySelectorAll("th[data-kf-sort]")) {
      th.addEventListener("click", () => {
        const path = th.getAttribute("data-kf-sort");
        const dir = node._kfSort && node._kfSort.path === path &&
          node._kfSort.dir === "asc" ? "desc" : "asc";
        node._kfSort = { path, dir };
        for (const o of node.querySelectorAll("th[data-kf-sort]")) o.removeAttribute("aria-sort");
        th.setAttribute("aria-sort", dir === "asc" ? "ascending" : "descending");
        if (node._kfLast !== undefined) render(node._kfLast);
      });
    }
    refresh().catch((e) => kf.snack(String(e.message || e), "error"));
    if (pollMs > 0) node._kfPoller = kf.poller(refresh, pollMs);
  }

  // Substitute {placeholders} into a cloned row fragment and wire actions.
  function materialize(fragment, ctx) {
    const walker = document.createTreeWalker(fragment, NodeFilter.SHOW_TEXT);
    const texts = [];
    while (walker.nextNode()) texts.push(walker.currentNode);
    for (const t of texts) t.textContent = subst(t.textContent, ctx);
    for (const eln of fragment.querySelectorAll("*")) {
      for (const attr of [...eln.attributes]) {
        if (!attr.value.includes("{")) continue;
        // Body templates are JSON: substituted values must be escaped so
        // quotes/backslashes in data can't break JSON.parse at click time.
        const fill = attr.name === "data-kf-body" ? substJson : subst;
        eln.setAttribute(attr.name, fill(attr.value, ctx));
      }
      // show-when="{expr}"=value : remove the element unless it matches
      const showWhen = eln.getAttribute("data-kf-show-when");
      if (showWhen !== null) {
        const [got, want] = showWhen.split("==");
        if (got !== want) { eln.remove(); continue; }
      }
      const hideWhen = eln.getAttribute("data-kf-hide-when");
      if (hideWhen !== null) {
        const [got, want] = hideWhen.split("==");
        if (got === want) { eln.remove(); continue; }
      }
      const statusVal = eln.getAttribute("data-kf-status");
      if (statusVal !== null) applyStatus(eln, statusVal);
      if (eln.hasAttribute("data-kf-action")) wireAction(eln, ctx);
    }
  }

  // data-kf-status="{status.phase}" — status icon: phase-keyed class +
  // glyph (reference: common-lib status icons / status.component.ts).
  const STATUS_GLYPHS = {
    running: "●", ready: "●", succeeded: "●",
    waiting: "◌", pending: "◌", creating: "◌", unknown: "◌",
    failed: "✕", error: "✕", stopped: "■",
  };
  function applyStatus(eln, value) {
    const key = String(value || "unknown").toLowerCase();
    eln.classList.add("kf-status", "kf-status-" + key);
    if (!eln.textContent.trim()) eln.textContent = STATUS_GLYPHS[key] || "●";
    eln.setAttribute("title", value);
  }

  // ---- component: action buttons -------------------------------------------
  function wireAction(btn, ctx) {
    btn.addEventListener("click", async (ev) => {
      ev.preventDefault();
      const [method, ...rest] = btn.getAttribute("data-kf-action").split(":");
      const url = subst(rest.join(":"), ctx || {});
      const confirmTpl = btn.getAttribute("data-kf-confirm");
      if (confirmTpl && !(await kf.confirm(subst(confirmTpl, ctx || {})))) return;
      try {
        let body;
        const bodyTpl = btn.getAttribute("data-kf-body");
        if (bodyTpl) body = JSON.parse(substJson(bodyTpl, ctx || {}));
        const result = await kf.api(method, url, body);
        kf.snack(btn.getAttribute("data-kf-done") || "done", "ok");
        runThen(btn.getAttribute("data-kf-then"), result);
      } catch (e) {
        kf.snack(String(e.message || e), "error");
      }
    });
  }

  function runThen(thenSpec, result) {
    if (!thenSpec || thenSpec === "none") return;
    for (const step of thenSpec.split(",")) {
      const [verb, arg] = step.split(":");
      if (verb === "refresh") {
        const target = document.querySelector(arg);
        if (target && target._kfRefresh) {
          target._kfRefresh().catch(() => {});
        } else if (target && target._kfInit) {
          target._kfInit().catch(() => {});
        }
      } else if (verb === "render") {
        // Render the MUTATION's own response into the target collection —
        // the server already computed the post-write view (with its
        // read-your-writes barrier), so a refetch here would only race
        // the informer mirror.
        const target = document.querySelector(arg);
        if (target && target._kfRender) target._kfRender(result);
      } else if (verb === "reload") {
        location.reload();
      } else if (verb === "nav") {
        location.href = subst(arg, {});
      } else if (verb === "clear") {
        const form = document.querySelector(arg);
        if (form) form.reset();
      }
    }
  }

  // ---- component: forms ----------------------------------------------------
  function formBody(form) {
    const body = {};
    for (const field of form.querySelectorAll("[name]")) {
      if (field.disabled) continue;
      let value;
      if (field.tagName === "SELECT" && field.multiple) {
        value = [...field.selectedOptions].map((o) => o.value);
      } else if (field.type === "checkbox") {
        value = field.checked;
      } else if (field.type === "number") {
        value = field.value === "" ? "" : Number(field.value);
      } else {
        value = field.value;
      }
      const omitIf = field.getAttribute("data-kf-omit-if");
      if (omitIf !== null && String(value) === omitIf) continue;
      if (value === "" && field.hasAttribute("data-kf-omit-empty")) continue;
      // omit-unless: drop this field while the referenced control is empty
      // (e.g. a volume's type select only counts once a name is typed).
      const unless = field.getAttribute("data-kf-omit-unless");
      if (unless) {
        const dep = form.querySelector(unless) || document.querySelector(unless);
        if (!dep || !dep.value) continue;
      }
      // Dotted names nest; NUMERIC segments index arrays
      // (dataVolumes.0.name -> {dataVolumes: [{name: ...}]}).
      const path = field.getAttribute("name").split(".");
      let cur = body;
      for (let i = 0; i < path.length - 1; i++) {
        const seg = path[i];
        const wantArray = /^\d+$/.test(path[i + 1]);
        if (/^\d+$/.test(seg)) {
          if (!Array.isArray(cur)) {
            // mixed array/object segments under one key is an authoring
            // bug — fail loudly (JSON.stringify would silently drop it)
            throw new Error("form name mixes array and object segments: " + field.getAttribute("name"));
          }
          const idx = +seg;
          while (cur.length <= idx) cur.push(wantArray ? [] : {});
          cur = cur[idx];
        } else {
          if (Array.isArray(cur)) {
            throw new Error("form name mixes array and object segments: " + field.getAttribute("name"));
          }
          if (!(seg in cur)) cur[seg] = wantArray ? [] : {};
          cur = cur[seg];
        }
      }
      const leaf = path[path.length - 1];
      if (/^\d+$/.test(leaf) !== Array.isArray(cur)) {
        throw new Error("form name mixes array and object segments: " + field.getAttribute("name"));
      }
      if (/^\d+$/.test(leaf)) {
        const idx = +leaf;
        while (cur.length <= idx) cur.push(null);
        cur[idx] = value;
      } else {
        cur[leaf] = value;
      }
    }
    return body;
  }
  kf.formBody = formBody;

  // data-kf-validate="required pattern:<re> min:<n> max:<n>" — submit-time
  // per-field validation with inline .kf-error messages (reference:
  // common-lib form validators + mat-error rendering). Rules are
  // SPACE-separated (| belongs to regex alternation in pattern rules).
  function validateField(field) {
    const rules = (field.getAttribute("data-kf-validate") || "").split(/\s+/).filter(Boolean);
    const v = field.type === "checkbox" ? String(field.checked) : field.value;
    for (const rule of rules) {
      const [name, ...rest] = rule.split(":");
      const arg = rest.join(":");
      if (name === "required" && !v) return "required";
      if (name === "pattern" && v && !new RegExp("^(?:" + arg + ")$").test(v)) {
        return field.getAttribute("data-kf-error") || "invalid format";
      }
      if ((name === "min" || name === "max") && v !== "") {
        if (isNaN(Number(v))) return "must be a number";
        if (name === "min" && Number(v) < Number(arg)) return "min " + arg;
        if (name === "max" && Number(v) > Number(arg)) return "max " + arg;
      }
    }
    return null;
  }
  function validateForm(form) {
    let ok = true;
    for (const field of form.querySelectorAll("[data-kf-validate]")) {
      let err = field.nextElementSibling;
      if (!(err && err.classList && err.classList.contains("kf-error"))) {
        err = document.createElement("span");
        err.className = "kf-error";
        field.after(err);
      }
      const msg = validateField(field);
      err.textContent = msg || "";
      field.classList.toggle("kf-invalid", !!msg);
      if (msg) ok = false;
    }
    return ok;
  }

  function initForm(form) {
    form.addEventListener("submit", async (ev) => {
      ev.preventDefault();
      if (!validateForm(form)) return; // inline errors rendered, no HTTP
      const [method, ...rest] = form.getAttribute("data-kf-form").split(":");
      const url = subst(rest.join(":"), {});
      try {
        const result = await kf.api(method, url, formBody(form));
        kf.snack(form.getAttribute("data-kf-done") || "created", "ok");
        runThen(form.getAttribute("data-kf-then"), result);
      } catch (e) {
        kf.snack(String(e.message || e), "error");
      }
    });
  }

  // ---- component: data-driven selects / text / visibility ------------------
  async function initOptions(sel) {
    const [url, itemsPath, valuePath, labelTpl] =
      sel.getAttribute("data-kf-options").split(";");
    const depSel = sel.getAttribute("data-kf-depends");
    const load = async () => {
      const dep = depSel ? (document.querySelector(depSel) || {}).value : undefined;
      const ctx = { dep: dep === undefined ? "" : dep };
      const data = await kf.api("GET", subst(url, ctx));
      const items = itemsAt(data, subst(itemsPath, ctx), ctx);
      const keep = sel.hasAttribute("data-kf-keep-first") && sel.options.length
        ? [sel.options[0]] : [];
      sel.replaceChildren(...keep);
      for (const item of items) {
        const opt = document.createElement("option");
        opt.value = valuePath === "." ? String(item) : String(lookup(item, valuePath));
        opt.textContent = labelTpl ? subst(labelTpl, item) : opt.value;
        sel.append(opt);
      }
      sel.disabled = items.length === 0 && !keep.length;
    };
    sel._kfInit = load;
    await load().catch(() => {});
    if (depSel) {
      const dep = document.querySelector(depSel);
      if (dep) dep.addEventListener("change", () => load().catch(() => {}));
    }
  }

  // data-kf-value="/url;path" — set a form control's value (and its reset
  // default) from config, e.g. admin spawner defaults. Runs after
  // data-kf-options so a fetched default can select a fetched option.
  async function initValue(node) {
    const [url, path] = node.getAttribute("data-kf-value").split(";");
    try {
      const data = await kf.api("GET", subst(url, {}));
      const v = lookup(data, path);
      if (v === undefined || v === null) return;
      const s = String(v);
      node.value = s;
      if (node.tagName === "SELECT") {
        // defaultValue is a no-op on <select>: form.reset() restores
        // options' defaultSelected, so pin that instead.
        for (const opt of node.options) {
          opt.defaultSelected = opt.value === s;
          opt.selected = opt.value === s;
        }
      } else {
        node.defaultValue = s;
      }
    } catch (e) { /* keep the static default */ }
  }

  async function initText(node) {
    const [url, path, tpl] = node.getAttribute("data-kf-text").split(";");
    const load = async () => {
      if (!url) { // static template against the page context (e.g. {ns})
        node.textContent = subst(tpl || "", {});
        return;
      }
      const data = await kf.api("GET", subst(url, {}));
      node.textContent = tpl ? subst(tpl, data) : String(lookup(data, path) ?? "");
    };
    node._kfInit = load;
    await load().catch(() => {});
  }

  async function initShowIf(node) {
    const [url, path, want] = node.getAttribute("data-kf-show-if").split(";");
    const load = async () => {
      const data = await kf.api("GET", subst(url, {}));
      const got = String(lookup(data, path));
      node.style.display = got === want ? "" : "none";
      node.toggleAttribute("hidden", got !== want);
    };
    node._kfInit = load;
    await load().catch(() => {});
  }

  // ---- component: SVG bar chart (resource-chart.js analog) -----------------
  async function initChart(node) {
    const [url, itemsPath, labelPath, valuePath] =
      node.getAttribute("data-kf-chart").split(";");
    const pollMs = parseInt(node.getAttribute("data-kf-poll") || "0", 10);
    const load = async () => {
      const data = await kf.api("GET", subst(url, {}));
      const items = itemsAt(data, itemsPath, {});
      const W = 320, BAR = 18, GAP = 6;
      const H = items.length * (BAR + GAP) || BAR;
      const svgNS = "http://www.w3.org/2000/svg";
      const svg = document.createElementNS(svgNS, "svg");
      svg.setAttribute("viewBox", `0 0 ${W} ${H}`);
      svg.setAttribute("class", "kf-chart");
      items.forEach((item, i) => {
        const value = Number(lookup(item, valuePath)) || 0;
        const frac = Math.max(0, Math.min(1, value));
        const y = i * (BAR + GAP);
        const bg = document.createElementNS(svgNS, "rect");
        bg.setAttribute("x", "120"); bg.setAttribute("y", String(y));
        bg.setAttribute("width", String(W - 120)); bg.setAttribute("height", String(BAR));
        bg.setAttribute("class", "kf-bar-bg");
        const bar = document.createElementNS(svgNS, "rect");
        bar.setAttribute("x", "120"); bar.setAttribute("y", String(y));
        bar.setAttribute("width", String((W - 120) * frac));
        bar.setAttribute("height", String(BAR));
        bar.setAttribute("class", "kf-bar");
        const label = document.createElementNS(svgNS, "text");
        label.setAttribute("x", "0"); label.setAttribute("y", String(y + BAR - 4));
        label.setAttribute("class", "kf-bar-label");
        label.textContent = String(lookup(item, labelPath) ?? "");
        const pct = document.createElementNS(svgNS, "text");
        pct.setAttribute("x", String(W - 4)); pct.setAttribute("y", String(y + BAR - 4));
        pct.setAttribute("text-anchor", "end");
        pct.setAttribute("class", "kf-bar-pct");
        pct.textContent = Math.round(frac * 100) + "%";
        svg.append(bg, bar, label, pct);
      });
      node.replaceChildren(svg);
    };
    node._kfRefresh = load;
    await load().catch(() => {});
    if (pollMs > 0) node._kfPoller = kf.poller(load, pollMs);
  }

  // data-kf-chart-line="/url;itemsPath;labelPath;valuePath" — rolling
  // time-series chart: each load appends one [0,1] sample per series label
  // to a client-side window (data-kf-window, default 30) and renders one
  // polyline per series. The reference's resource-chart.js keeps the same
  // client-side sliding sample window (resource-chart.js:1-353).
  async function initChartLine(node) {
    const [url, itemsPath, labelPath, valuePath] =
      node.getAttribute("data-kf-chart-line").split(";");
    const windowN = parseInt(node.getAttribute("data-kf-window") || "30", 10);
    const pollMs = parseInt(node.getAttribute("data-kf-poll") || "0", 10);
    node._kfHistory = {};
    const load = async () => {
      const data = await kf.api("GET", subst(url, {}));
      for (const item of itemsAt(data, itemsPath, {})) {
        const label = String(lookup(item, labelPath));
        const v = Math.max(0, Math.min(1, Number(lookup(item, valuePath)) || 0));
        const h = (node._kfHistory[label] = node._kfHistory[label] || []);
        h.push(v);
        if (h.length > windowN) h.shift();
      }
      const svgNS = "http://www.w3.org/2000/svg";
      const svg = document.createElementNS(svgNS, "svg");
      svg.setAttribute("viewBox", "0 0 100 44");
      svg.setAttribute("class", "kf-chart-line");
      const step = windowN > 1 ? 100 / (windowN - 1) : 100;
      let si = 0;
      for (const [label, h] of Object.entries(node._kfHistory)) {
        const line = document.createElementNS(svgNS, "polyline");
        line.setAttribute("class", "kf-line kf-line-" + (si % 8));
        line.setAttribute("data-series", label);
        line.setAttribute("points",
          h.map((v, i) => (i * step).toFixed(2) + "," + (42 - v * 40).toFixed(2)).join(" "));
        const text = document.createElementNS(svgNS, "text");
        text.setAttribute("x", "0");
        text.setAttribute("y", String(6 + si * 6));
        text.setAttribute("class", "kf-line-label");
        text.textContent = label + " " + Math.round(h[h.length - 1] * 100) + "%";
        svg.append(line, text);
        si += 1;
      }
      node.replaceChildren(svg);
    };
    node._kfRefresh = load;
    await load().catch(() => {});
    if (pollMs > 0) node._kfPoller = kf.poller(load, pollMs);
  }

  // ---- component: namespace selector (namespace-selector.js analog) --------
  async function initNsSelect(sel) {
    const data = await kf.api("GET", "/api/namespaces").catch(() => []);
    const namespaces = Array.isArray(data) ? data : [];
    sel.replaceChildren();
    for (const ns of namespaces) {
      const opt = document.createElement("option");
      opt.value = ns; opt.textContent = ns;
      sel.append(opt);
    }
    const current = kf.ns();
    if (namespaces.includes(current)) sel.value = current;
    sel.addEventListener("change", () => {
      const u = new URL(location.href);
      u.searchParams.set("ns", sel.value);
      location.href = u.toString();
    });
  }

  // ---- boot ----------------------------------------------------------------
  kf.init = async function (root) {
    root = root || document;
    kf._initMemo = {};
    try {
      await kf._initAll(root);
    } finally {
      kf._initMemo = null;
    }
  };

  // Handler bodies are hand-written above; WHICH selectors initialize, in
  // WHAT order, and with what defaults is owned by kfspec.json's dispatch
  // section (the generated block below) — e2e/uidom.py builds its
  // interpreter loop from the same section at runtime, so the two
  // runtimes cannot disagree about dispatch.
  kf._handlers = {
    nav: async (a) => {
      const target = a.getAttribute("data-kf-nav");
      a.setAttribute("href", target + "?ns=" + encodeURIComponent(kf.ns()));
    },
    ns_select: initNsSelect,
    options: initOptions,
    value: initValue,
    text: initText,
    show_if: initShowIf,
    chart: initChart,
    chart_line: initChartLine,
    table: async (n) => initTable(n),
    form: async (n) => initForm(n),
    // page-level action buttons (row-level ones are wired by materialize)
    action: async (n) => {
      if (!n.closest("template") && !n._kfWired) { n._kfWired = true; wireAction(n, {}); }
    },
  };

  // BEGIN GENERATED (kfspec.json dispatch; python -m e2e.uidom --gen-dispatch) — DO NOT EDIT
  kf.DEFAULTS = {"poll_ms": 3000, "poll_max_ms": 30000, "snack_ms": 4000, "empty_text": "none", "items_path": "."};
  kf.DISPATCH = [
    {"selector": "[data-kf-nav]", "handler": "nav", "binding": "init"},
    {"selector": "[data-kf-ns-select]", "handler": "ns_select", "binding": "init"},
    {"selector": "[data-kf-options]", "handler": "options", "binding": "init"},
    {"selector": "[data-kf-value]", "handler": "value", "binding": "init"},
    {"selector": "[data-kf-text]", "handler": "text", "binding": "init"},
    {"selector": "[data-kf-show-if]", "handler": "show_if", "binding": "init"},
    {"selector": "[data-kf-chart]", "handler": "chart", "binding": "init"},
    {"selector": "[data-kf-chart-line]", "handler": "chart_line", "binding": "init"},
    {"selector": "[data-kf-table]", "handler": "table", "binding": "init"},
    {"selector": "form[data-kf-form]", "handler": "form", "binding": "event"},
    {"selector": "[data-kf-action]", "handler": "action", "binding": "event"},
  ];
  kf._initAll = async function (root) {
    for (const entry of kf.DISPATCH) {
      const handler = kf._handlers[entry.handler];
      for (const n of root.querySelectorAll(entry.selector)) await handler(n);
    }
  };
  // END GENERATED

  if (document.readyState === "loading") {
    document.addEventListener("DOMContentLoaded", () => kf.init());
  } else {
    kf.init();
  }
})();
