// Shared SPA runtime for the platform web apps (the kubeflow-common-lib
// analog, reference: crud-web-apps/common/frontend — reduced to the pieces
// the backends actually serve: fetch with identity passthrough + CSRF
// double-submit, table rendering, status badges, polling).
"use strict";

function cookie(name) {
  const m = document.cookie.match(new RegExp("(?:^|; )" + name + "=([^;]*)"));
  return m ? decodeURIComponent(m[1]) : null;
}

async function api(method, path, body) {
  const headers = { "content-type": "application/json" };
  const token = cookie("XSRF-TOKEN");
  if (token) headers["x-xsrf-token"] = token;
  const resp = await fetch(path, {
    method,
    headers,
    credentials: "same-origin",
    body: body === undefined ? undefined : JSON.stringify(body),
  });
  const text = await resp.text();
  const data = text ? JSON.parse(text) : null;
  if (!resp.ok) throw new Error((data && data.error) || resp.statusText);
  return data;
}

function el(tag, attrs, ...children) {
  const node = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (k === "onclick") node.onclick = v;
    else node.setAttribute(k, v);
  }
  for (const c of children) {
    node.append(c instanceof Node ? c : document.createTextNode(String(c)));
  }
  return node;
}

function renderTable(mount, columns, rows) {
  const table = el("table", { class: "tbl" });
  table.append(
    el("thead", {}, el("tr", {}, ...columns.map((c) => el("th", {}, c.title))))
  );
  const tbody = el("tbody");
  for (const row of rows) {
    tbody.append(el("tr", {}, ...columns.map((c) => el("td", {}, c.render(row)))));
  }
  if (!rows.length) {
    tbody.append(
      el("tr", {}, el("td", { colspan: String(columns.length), class: "empty" }, "none"))
    );
  }
  table.append(tbody);
  mount.replaceChildren(table);
}

function statusBadge(phase) {
  return el("span", { class: "badge badge-" + phase }, phase);
}

function nsParam() {
  return new URLSearchParams(location.search).get("ns") || "kubeflow-user";
}

function poll(fn, ms) {
  fn().catch(() => {});
  return setInterval(() => fn().catch(() => {}), ms || 3000);
}
