"""SPA index serving — the crud_backend ``serving.py`` contract
(reference: crud-web-apps/common/.../serving.py:18-31): the index page is
served with an ETag and ``Cache-Control: no-cache`` (clients revalidate
every load, 304 when unchanged) and every index response refreshes the
CSRF double-submit cookie so the SPA can immediately make unsafe calls.
"""

from __future__ import annotations

import hashlib
import os
from typing import Optional

from .auth import AuthConfig, issue_csrf_cookie
from .http import App, JsonResponse, Request

UI_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "ui")


def load_ui(name: str) -> str:
    """Load a UI page, inlining the shared runtime (single-file responses —
    no extra asset routes to secure or cache)."""
    with open(os.path.join(UI_DIR, name)) as f:
        html = f.read()
    for fname, open_tag, close_tag in (
        ("kfui.js", "<script>", "</script>"),
        ("style.css", "<style>", "</style>"),
    ):
        include = f"<!--#include {fname}-->"
        if include in html:
            with open(os.path.join(UI_DIR, fname)) as f:
                html = html.replace(include, f"{open_tag}\n{f.read()}\n{close_tag}")
    return html


def install_spa(app: App, html: str, cfg: Optional[AuthConfig] = None,
                paths: tuple = ("/", "/index.html")) -> None:
    cfg = cfg or AuthConfig()
    etag = '"' + hashlib.sha256(html.encode()).hexdigest()[:32] + '"'

    def serve_index(req: Request) -> JsonResponse:
        if req.header("if-none-match") == etag:
            resp = JsonResponse(None, status=304)
        else:
            resp = JsonResponse(html, headers={"Content-Type": "text/html; charset=utf-8"})
        resp.headers["ETag"] = etag
        resp.headers["Cache-Control"] = "no-cache"
        issue_csrf_cookie(resp, cfg)
        return resp

    for path in paths:
        app.route(path)(serve_index)
