"""API version conversion: hub-and-spoke, served at the REST layer.

The reference's Notebook CRD serves three versions converting through a
storage hub (api/v1/notebook_conversion.go:24-69 — v1 and v1alpha1 convert
to/from v1beta1; the schemas are structurally identical, so conversion is
the apiVersion stamp plus any registered field mappers). Same model here:
spoke versions are registered REST surfaces; objects are STORED at the hub
version only; the apiserver converts on the way in and out.

In-process clients (controllers) always speak the hub version — conversion
is an API-server concern, exactly as in Kubernetes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from . import meta as apimeta
from .meta import REGISTRY, Resource

#: (group, kind) -> hub (storage) version
_HUBS: Dict[Tuple[str, str], str] = {}

#: (group, kind, from_version, to_version) -> field mapper (post stamp-swap)
_MAPPERS: Dict[Tuple[str, str, str, str], Callable[[Dict[str, Any]], Dict[str, Any]]] = {}


def register_spokes(group: str, kind: str, hub_version: str, *spoke_versions: str) -> None:
    """Declare spoke versions for a kind whose hub Resource is registered."""
    hub = REGISTRY.for_gvk(apimeta.GroupVersionKind(group, hub_version, kind))
    _HUBS[(group, kind)] = hub_version
    for version in spoke_versions:
        REGISTRY.register(
            Resource(group, version, kind, hub.plural, namespaced=hub.namespaced,
                     list_kind=hub.list_kind)
        )


def register_mapper(group: str, kind: str, from_version: str, to_version: str,
                    fn: Callable[[Dict[str, Any]], Dict[str, Any]]) -> None:
    _MAPPERS[(group, kind, from_version, to_version)] = fn


def hub_version(group: str, kind: str) -> Optional[str]:
    return _HUBS.get((group, kind))


def hub_resource(res: Resource) -> Resource:
    """The storage Resource for ``res`` (itself if it IS the hub or has none)."""
    hub = _HUBS.get((res.group, res.kind))
    if hub is None or hub == res.version:
        return res
    return REGISTRY.for_gvk(apimeta.GroupVersionKind(res.group, hub, res.kind))


def convert(obj: Dict[str, Any], group: str, kind: str, to_version: str) -> Dict[str, Any]:
    """Convert between served versions: stamp swap + registered mapper."""
    current = apimeta.gvk_of(obj).version
    if current == to_version:
        return obj
    out = apimeta.deepcopy(obj)
    out["apiVersion"] = f"{group}/{to_version}" if group else to_version
    mapper = _MAPPERS.get((group, kind, current, to_version))
    if mapper is not None:
        out = mapper(out)
    return out


def convert_fragment(
    fragment: Dict[str, Any], group: str, kind: str, from_version: str, to_version: str
) -> Dict[str, Any]:
    """Convert a PARTIAL object (merge-patch body) between versions.

    Mappers must tolerate partial objects (missing sections untouched) —
    the contract a merge patch at a spoke endpoint needs so version-specific
    field renames apply before the merge into hub storage."""
    if from_version == to_version:
        return fragment
    mapper = _MAPPERS.get((group, kind, from_version, to_version))
    if mapper is None:
        return fragment
    return mapper(apimeta.deepcopy(fragment))


# --- platform registrations --------------------------------------------------
# Notebook: hub v1beta1, spokes v1alpha1 + v1 (reference hub-and-spoke —
# notebook-controller registers 3 API versions, main.go:40-47; conversion is
# structural identity, api/v1/notebook_conversion.go).
register_spokes("kubeflow.org", "Notebook", "v1beta1", "v1alpha1", "v1")
