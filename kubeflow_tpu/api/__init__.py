from .meta import (  # noqa: F401
    GroupVersionKind,
    Resource,
    REGISTRY,
    api_version_of,
    gvk_of,
    match_label_selector,
    matches_selector,
    name_of,
    namespace_of,
    new_object,
    owner_reference,
    set_owner_reference,
)
