"""API machinery: object model, type registry, selectors.

Objects are plain dicts in Kubernetes wire shape (``apiVersion``, ``kind``,
``metadata``, ``spec``, ``status``) — the "unstructured" representation. The
registry maps kinds to their REST resource coordinates so clients, the store,
and controllers agree on addressing. Mirrors the role of the reference's Go
scheme/typed clients (e.g. components/access-management/kfam/profiles.go:24-30)
without code generation.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional


@dataclass(frozen=True)
class GroupVersionKind:
    group: str
    version: str
    kind: str

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


@dataclass(frozen=True)
class Resource:
    """REST coordinates for a kind."""

    group: str
    version: str
    kind: str
    plural: str
    namespaced: bool = True
    list_kind: str = ""

    @property
    def api_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version

    @property
    def gvk(self) -> GroupVersionKind:
        return GroupVersionKind(self.group, self.version, self.kind)

    @property
    def key(self) -> str:
        """Storage/watch key prefix: group/version/plural."""
        return f"{self.group or 'core'}/{self.version}/{self.plural}"


class ResourceRegistry:
    def __init__(self) -> None:
        self._by_gvk: Dict[GroupVersionKind, Resource] = {}
        self._by_plural: Dict[tuple, Resource] = {}  # (apiVersion, plural)

    def register(self, res: Resource) -> Resource:
        self._by_gvk[res.gvk] = res
        self._by_plural[(res.api_version, res.plural)] = res
        return res

    def for_object(self, obj: Dict[str, Any]) -> Resource:
        return self.for_gvk(gvk_of(obj))

    def for_gvk(self, gvk: GroupVersionKind) -> Resource:
        try:
            return self._by_gvk[gvk]
        except KeyError:
            raise KeyError(f"kind not registered: {gvk}") from None

    def for_kind(self, api_version: str, kind: str) -> Resource:
        group, _, version = api_version.rpartition("/")
        return self.for_gvk(GroupVersionKind(group, version, kind))

    def for_plural(self, api_version: str, plural: str) -> Resource:
        try:
            return self._by_plural[(api_version, plural)]
        except KeyError:
            raise KeyError(f"resource not registered: {api_version}/{plural}") from None

    def all(self) -> List[Resource]:
        return list(self._by_gvk.values())


REGISTRY = ResourceRegistry()

# --- Built-in kinds (the subset of core Kubernetes the platform touches) ----
for _res in [
    Resource("", "v1", "Pod", "pods"),
    Resource("", "v1", "Service", "services"),
    Resource("", "v1", "Endpoints", "endpoints"),
    Resource("", "v1", "Namespace", "namespaces", namespaced=False),
    Resource("", "v1", "Node", "nodes", namespaced=False),
    Resource("", "v1", "Event", "events"),
    Resource("", "v1", "ConfigMap", "configmaps"),
    Resource("", "v1", "Secret", "secrets"),
    Resource("", "v1", "PersistentVolumeClaim", "persistentvolumeclaims"),
    Resource("", "v1", "ServiceAccount", "serviceaccounts"),
    Resource("", "v1", "ResourceQuota", "resourcequotas"),
    Resource("apps", "v1", "StatefulSet", "statefulsets"),
    Resource("apps", "v1", "Deployment", "deployments"),
    Resource("rbac.authorization.k8s.io", "v1", "Role", "roles"),
    Resource("rbac.authorization.k8s.io", "v1", "RoleBinding", "rolebindings"),
    Resource("rbac.authorization.k8s.io", "v1", "ClusterRole", "clusterroles", namespaced=False),
    Resource(
        "rbac.authorization.k8s.io", "v1", "ClusterRoleBinding", "clusterrolebindings", namespaced=False
    ),
    Resource("storage.k8s.io", "v1", "StorageClass", "storageclasses", namespaced=False),
    # Dynamic admission registration (reference: admission-webhook/manifests/
    # base/mutating-webhook-configuration.yaml:1-23) — the apiserver watches
    # these instead of being wired by a WEBHOOK_URL env (VERDICT r4 #5).
    Resource(
        "admissionregistration.k8s.io", "v1", "MutatingWebhookConfiguration",
        "mutatingwebhookconfigurations", namespaced=False,
    ),
    # Controller HA leases (reference: -enable-leader-election on every
    # controller binary, notebook-controller/main.go:55-66).
    Resource("coordination.k8s.io", "v1", "Lease", "leases"),
    # Istio objects the controllers emit (stored as unstructured, same as the
    # reference does via the dynamic client — notebook_controller.go:401-496).
    Resource("networking.istio.io", "v1beta1", "VirtualService", "virtualservices"),
    Resource("security.istio.io", "v1beta1", "AuthorizationPolicy", "authorizationpolicies"),
    # Platform CRDs (see kubeflow_tpu/api/crds.py for schemas).
    Resource("kubeflow.org", "v1beta1", "Notebook", "notebooks"),
    Resource("kubeflow.org", "v1", "Profile", "profiles", namespaced=False),
    Resource("tensorboard.kubeflow.org", "v1alpha1", "Tensorboard", "tensorboards"),
    Resource("kubeflow.org", "v1alpha1", "PodDefault", "poddefaults"),
    Resource("katib.kubeflow.org", "v1alpha1", "StudyJob", "studyjobs"),
    Resource("katib.kubeflow.org", "v1alpha1", "Trial", "trials"),
    Resource("serving.kubeflow.org", "v1alpha1", "InferenceService", "inferenceservices"),
]:
    REGISTRY.register(_res)


# --- Object helpers ---------------------------------------------------------


def new_object(
    api_version: str,
    kind: str,
    name: str,
    namespace: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    **top_level: Any,
) -> Dict[str, Any]:
    meta: Dict[str, Any] = {"name": name}
    if namespace is not None:
        meta["namespace"] = namespace
    if labels:
        meta["labels"] = dict(labels)
    if annotations:
        meta["annotations"] = dict(annotations)
    obj: Dict[str, Any] = {"apiVersion": api_version, "kind": kind, "metadata": meta}
    obj.update(top_level)
    return obj


def now_rfc3339() -> str:
    """RFC3339 with microseconds (metav1.MicroTime) — Lease renewTime needs
    sub-second resolution so rapid renewals are distinguishable."""
    import datetime as _dt

    return _dt.datetime.now(_dt.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


def gvk_of(obj: Dict[str, Any]) -> GroupVersionKind:
    api_version = obj.get("apiVersion", "")
    group, _, version = api_version.rpartition("/")
    return GroupVersionKind(group, version, obj.get("kind", ""))


def api_version_of(obj: Dict[str, Any]) -> str:
    return obj.get("apiVersion", "")


def name_of(obj: Dict[str, Any]) -> str:
    return obj.get("metadata", {}).get("name", "")


def namespace_of(obj: Dict[str, Any]) -> Optional[str]:
    return obj.get("metadata", {}).get("namespace")


def uid_of(obj: Dict[str, Any]) -> str:
    return obj.get("metadata", {}).get("uid", "")


def labels_of(obj: Dict[str, Any]) -> Dict[str, str]:
    return obj.get("metadata", {}).get("labels") or {}


def annotations_of(obj: Dict[str, Any]) -> Dict[str, str]:
    return obj.get("metadata", {}).get("annotations") or {}


def owner_reference(owner: Dict[str, Any], controller: bool = True) -> Dict[str, Any]:
    return {
        "apiVersion": api_version_of(owner),
        "kind": owner.get("kind", ""),
        "name": name_of(owner),
        "uid": uid_of(owner),
        "controller": controller,
        "blockOwnerDeletion": True,
    }


def set_owner_reference(obj: Dict[str, Any], owner: Dict[str, Any]) -> Dict[str, Any]:
    refs = obj.setdefault("metadata", {}).setdefault("ownerReferences", [])
    ref = owner_reference(owner)
    for existing in refs:
        if existing.get("uid") == ref["uid"] and existing.get("name") == ref["name"]:
            return obj
    refs.append(ref)
    return obj


def controller_owner_of(obj: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    for ref in obj.get("metadata", {}).get("ownerReferences", []) or []:
        if ref.get("controller"):
            return ref
    return None


def deepcopy(obj: Dict[str, Any]) -> Dict[str, Any]:
    return copy.deepcopy(obj)


# --- Label selectors --------------------------------------------------------
# Full LabelSelector semantics (matchLabels + matchExpressions with
# In/NotIn/Exists/DoesNotExist), as consumed by the PodDefault webhook
# (reference: admission-webhook/main.go:69-94).


def matches_selector(labels: Dict[str, str], selector: Optional[Dict[str, Any]]) -> bool:
    if selector is None:
        return True
    labels = labels or {}
    for key, value in (selector.get("matchLabels") or {}).items():
        if labels.get(key) != value:
            return False
    for expr in selector.get("matchExpressions") or []:
        key = expr.get("key", "")
        op = expr.get("operator", "")
        values = expr.get("values") or []
        if op == "In":
            if labels.get(key) not in values:
                return False
        elif op == "NotIn":
            if key in labels and labels[key] in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            raise ValueError(f"unknown selector operator: {op!r}")
    return True


def match_label_selector(
    objects: Iterable[Dict[str, Any]], selector: Optional[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    return [o for o in objects if matches_selector(labels_of(o), selector)]


def parse_selector_string(sel: str) -> Dict[str, str]:
    """Parse ``k1=v1,k2=v2`` query-string selectors (list/watch requests)."""
    out: Dict[str, str] = {}
    for part in sel.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad selector segment: {part!r}")
        k, _, v = part.partition("=")
        out[k.strip()] = v.strip().lstrip("=")
    return out


@dataclass
class Condition:
    """Status condition helper (Profile/Notebook conditions —
    profile-controller api/v1/profile_types.go:49-53)."""

    type: str
    status: str = "True"
    reason: str = ""
    message: str = ""
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self, now: str) -> Dict[str, Any]:
        d = {
            "type": self.type,
            "status": self.status,
            "lastTransitionTime": now,
        }
        if self.reason:
            d["reason"] = self.reason
        if self.message:
            d["message"] = self.message
        d.update(self.extra)
        return d
