// Native storage core for the in-process API server.
//
// The reference's control plane is compiled (five Go binaries — SURVEY.md
// §2.9); this is the TPU build's native runtime core: an MVCC object store
// with a replayable write journal, built as a C shared library and bound
// from Python via ctypes (kubeflow_tpu/apiserver/backend.py).
//
// Responsibilities (the storage hot path):
//   - buckets of (namespace, name) -> {opaque blob, labels, revision},
//   - a global monotonically increasing resourceVersion counter,
//   - equality label-selector matching during list (without handing every
//     object back to Python for filtering),
//   - a bounded write journal keyed by revision — watchers can resume from
//     a resourceVersion the way etcd watch windows work (the pure-Python
//     fallback backend cannot replay history).
//
// Object semantics (admission, finalizers, status merge, GC) stay in
// Python: blobs are opaque here. Wire formats across the ctypes boundary:
//   labels/selector:  "k=v\x1fk2=v2"      (unit separator between pairs)
//   list result:      blob \x1e blob ...  (record separator between blobs)
//   journal records:  rv \x1f op \x1f bucket \x1f ns \x1f name \x1f blob,
//                     records joined by \x1e
// Blobs are JSON produced by json.dumps, which escapes control characters,
// so 0x1e/0x1f never appear inside a blob.

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

namespace {

constexpr char kRecordSep = '\x1e';
constexpr char kUnitSep = '\x1f';

struct Entry {
  std::string blob;
  std::map<std::string, std::string> labels;
  uint64_t rv = 0;
};

struct JournalEntry {
  uint64_t rv = 0;
  int op = 0;  // 0 ADDED, 1 MODIFIED, 2 DELETED (assigned by the caller)
  std::string bucket;
  std::string ns;
  std::string name;
  std::string blob;
};

using Key = std::pair<std::string, std::string>;  // (namespace, name)

struct StoreCore {
  std::mutex mu;
  uint64_t rv = 0;
  std::map<std::string, std::map<Key, Entry>> buckets;
  std::deque<JournalEntry> journal;
  size_t journal_cap = 65536;
};

std::map<std::string, std::string> parse_pairs(const char* s) {
  std::map<std::string, std::string> out;
  if (s == nullptr || *s == '\0') return out;
  const std::string text(s);
  size_t start = 0;
  while (start <= text.size()) {
    size_t end = text.find(kUnitSep, start);
    const std::string pair =
        text.substr(start, end == std::string::npos ? std::string::npos : end - start);
    size_t eq = pair.find('=');
    if (eq != std::string::npos) {
      out[pair.substr(0, eq)] = pair.substr(eq + 1);
    }
    if (end == std::string::npos) break;
    start = end + 1;
  }
  return out;
}

bool selector_matches(const std::map<std::string, std::string>& labels,
                      const std::map<std::string, std::string>& selector) {
  for (const auto& kv : selector) {
    auto it = labels.find(kv.first);
    if (it == labels.end() || it->second != kv.second) return false;
  }
  return true;
}

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out == nullptr) return nullptr;
  std::memcpy(out, s.data(), s.size());
  out[s.size()] = '\0';
  return out;
}

}  // namespace

extern "C" {

void* store_new() { return new StoreCore(); }

void store_destroy(void* h) { delete static_cast<StoreCore*>(h); }

uint64_t store_next_rv(void* h) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return ++s->rv;
}

uint64_t store_current_rv(void* h) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  return s->rv;
}

// Insert or replace; appends a journal record with the caller's op code and
// the entry's revision (which the caller must already have stamped into the
// blob via store_next_rv).
int store_put(void* h, const char* bucket, const char* ns, const char* name,
              const char* blob, const char* labels, uint64_t rv, int op) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  Entry e;
  e.blob = blob ? blob : "";
  e.labels = parse_pairs(labels);
  e.rv = rv;
  s->buckets[bucket][{ns ? ns : "", name ? name : ""}] = e;
  s->journal.push_back({rv, op, bucket, ns ? ns : "", name ? name : "", e.blob});
  while (s->journal.size() > s->journal_cap) s->journal.pop_front();
  return 0;
}

// Returns a malloc'd copy of the blob, or nullptr if absent.
char* store_get(void* h, const char* bucket, const char* ns, const char* name) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto b = s->buckets.find(bucket);
  if (b == s->buckets.end()) return nullptr;
  auto it = b->second.find({ns ? ns : "", name ? name : ""});
  if (it == b->second.end()) return nullptr;
  return dup_string(it->second.blob);
}

int store_contains(void* h, const char* bucket, const char* ns, const char* name) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto b = s->buckets.find(bucket);
  if (b == s->buckets.end()) return 0;
  return b->second.count({ns ? ns : "", name ? name : ""}) ? 1 : 0;
}

// Removes the entry and journals the caller-provided final blob (the object
// state at deletion, which may differ from the stored blob after a
// finalizer-driven update). Returns 0, or -1 if absent.
int store_delete(void* h, const char* bucket, const char* ns, const char* name,
                 const char* final_blob, uint64_t rv, int op) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto b = s->buckets.find(bucket);
  if (b == s->buckets.end()) return -1;
  auto it = b->second.find({ns ? ns : "", name ? name : ""});
  if (it == b->second.end()) return -1;
  std::string blob = final_blob ? final_blob : it->second.blob;
  b->second.erase(it);
  s->journal.push_back({rv, op, bucket, ns ? ns : "", name ? name : "", blob});
  while (s->journal.size() > s->journal_cap) s->journal.pop_front();
  return 0;
}

// Blobs of every entry in a bucket (optionally namespace- and
// selector-filtered), joined by the record separator. filter_by_ns is an
// explicit flag so the empty namespace ("" — cluster-scoped keys) remains
// distinguishable from "all namespaces".
char* store_list(void* h, const char* bucket, const char* ns, int filter_by_ns,
                 const char* selector) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  std::string out;
  auto sel = parse_pairs(selector);
  const bool filter_ns = filter_by_ns != 0;
  auto b = s->buckets.find(bucket);
  if (b != s->buckets.end()) {
    for (const auto& kv : b->second) {
      if (filter_ns && kv.first.first != (ns ? ns : "")) continue;
      if (!sel.empty() && !selector_matches(kv.second.labels, sel)) continue;
      if (!out.empty()) out.push_back(kRecordSep);
      out += kv.second.blob;
    }
  }
  return dup_string(out);
}

// Every entry in every bucket as "bucket \x1f blob" records (the GC sweep).
char* store_list_all(void* h) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  std::string out;
  for (const auto& bucket : s->buckets) {
    for (const auto& kv : bucket.second) {
      if (!out.empty()) out.push_back(kRecordSep);
      out += bucket.first;
      out.push_back(kUnitSep);
      out += kv.second.blob;
    }
  }
  return dup_string(out);
}

// Journal records with rv > since_rv, oldest first, at most max records,
// optionally filtered to one bucket (empty = all buckets — filtering here
// keeps a single-bucket resume from marshalling the whole journal).
// Returns nullptr (distinct from "") when since_rv has fallen out of the
// journal window — the caller must relist, exactly like an expired etcd
// watch.
char* store_journal_since(void* h, uint64_t since_rv, int max, const char* bucket) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  // Servable iff no record with rv > since_rv has been trimmed: trimmed
  // records all have rv < front().rv, so the window holds exactly when
  // since_rv >= front().rv - 1.
  if (!s->journal.empty() && since_rv + 1 < s->journal.front().rv) {
    return nullptr;  // window expired — caller must relist
  }
  const bool filter_bucket = (bucket != nullptr && *bucket != '\0');
  std::string out;
  int n = 0;
  for (const auto& je : s->journal) {
    if (je.rv <= since_rv) continue;
    if (filter_bucket && je.bucket != bucket) continue;
    if (max > 0 && n >= max) break;
    if (!out.empty()) out.push_back(kRecordSep);
    out += std::to_string(je.rv);
    out.push_back(kUnitSep);
    out += std::to_string(je.op);
    out.push_back(kUnitSep);
    out += je.bucket;
    out.push_back(kUnitSep);
    out += je.ns;
    out.push_back(kUnitSep);
    out += je.name;
    out.push_back(kUnitSep);
    out += je.blob;
    ++n;
  }
  return dup_string(out);
}

// Bound the journal window (testing + memory control; default 65536).
void store_set_journal_cap(void* h, uint64_t cap) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  s->journal_cap = cap == 0 ? 1 : static_cast<size_t>(cap);
  while (s->journal.size() > s->journal_cap) s->journal.pop_front();
}

uint64_t store_count(void* h, const char* bucket) {
  StoreCore* s = static_cast<StoreCore*>(h);
  std::lock_guard<std::mutex> lock(s->mu);
  auto b = s->buckets.find(bucket);
  return b == s->buckets.end() ? 0 : b->second.size();
}

void store_free_str(char* p) { std::free(p); }

}  // extern "C"
