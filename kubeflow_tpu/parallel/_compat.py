"""Version tolerance for the manual-SPMD surface (jax 0.4 <-> 0.8).

The parallelism modules are written against the modern ``jax.shard_map``
API (``check_vma`` + ``lax.pcast`` varying-type annotations). Older
installs (0.4.x, the floor the container images carry) expose the same
machinery as ``jax.experimental.shard_map.shard_map`` with ``check_rep``
and no varying-type system at all. Everything here resolves that drift in
one place so the callers stay on the modern spelling.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable

from jax import lax

try:
    from jax import shard_map as _shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters
if "check_vma" in _PARAMS:
    _UNCHECKED = {"check_vma": False}
elif "check_rep" in _PARAMS:  # jax 0.4.x spelling of the same escape hatch
    _UNCHECKED = {"check_rep": False}
else:  # pragma: no cover - future jax that dropped the knob entirely
    _UNCHECKED = {}


def shard_map_unchecked(
    f: Callable[..., Any], *, mesh: Any, in_specs: Any, out_specs: Any
) -> Callable[..., Any]:
    """``shard_map`` with replication/varying checking off, any jax."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **_UNCHECKED)


def pcast_varying(x: Any, axes: Any) -> Any:
    """Mark ``x`` device-varying over ``axes`` where the type system exists.

    Pre-vma jax (no ``lax.pcast``) has no varying types to satisfy; the
    value itself is already correct per-device, so pass it through.
    """
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return x
    return pcast(x, axes, to="varying")
