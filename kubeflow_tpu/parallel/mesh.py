"""Canonical device mesh construction for TPU slices.

One mesh axis vocabulary is used across the framework:

- ``data``   — pure data parallelism (gradients all-reduced; rides DCN
  across slices, ICI within one),
- ``fsdp``   — data parallelism with parameter/optimizer sharding
  (ZeRO-3 style; params all-gathered per layer, grads reduce-scattered),
- ``model``  — tensor parallelism (activations/weights split over ICI),
- ``seq``    — sequence/context parallelism (ring attention),
- ``pipe``   — pipeline parallelism (stage-partitioned layers, microbatch
  streaming via ``ppermute`` — parallel/pipeline.py),
- ``expert`` — expert parallelism for MoE layers (parallel/moe.py).

The reference control plane never builds meshes (SURVEY.md §2.10 — pod-level
delegation only); this module is the in-workload half the reference left to
CUDA images. Mesh geometry is chosen so the innermost axes map to ICI
neighbours (``jax.experimental.mesh_utils`` handles TPU physical layout) and
``data`` is outermost so its collectives can ride DCN across slices.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_PIPE = "pipe"
AXIS_EXPERT = "expert"

#: Order matters: outermost (slowest-varying, DCN-friendly) first; the
#: innermost axes land on physically adjacent chips for cheap collectives.
CANONICAL_AXES: Tuple[str, ...] = (AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_EXPERT, AXIS_SEQ, AXIS_MODEL)

#: Axes over which a batch is split (each holds a distinct slice of examples).
BATCH_AXES: Tuple[str, ...] = (AXIS_DATA, AXIS_FSDP)


@dataclass(frozen=True)
class MeshConfig:
    """Sizes for each canonical axis; unspecified axes default to 1.

    ``data=-1`` (or any single axis set to -1) means "whatever is left of
    the device count after the explicit axes", mirroring how users think
    about scaling out: fix model/seq parallelism, let dp absorb the rest.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    model: int = 1

    def sizes(self, num_devices: int) -> Dict[str, int]:
        raw = {
            AXIS_DATA: self.data,
            AXIS_FSDP: self.fsdp,
            AXIS_PIPE: self.pipe,
            AXIS_EXPERT: self.expert,
            AXIS_SEQ: self.seq,
            AXIS_MODEL: self.model,
        }
        wild = [a for a, s in raw.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1; got {wild}")
        fixed = 1
        for a, s in raw.items():
            if s != -1:
                if s < 1:
                    raise ValueError(f"mesh axis {a!r} must be >= 1 or -1, got {s}")
                fixed *= s
        if wild:
            if num_devices % fixed:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes product {fixed}"
                )
            raw[wild[0]] = num_devices // fixed
        total = int(np.prod(list(raw.values())))
        if total != num_devices:
            raise ValueError(
                f"mesh axes {raw} multiply to {total}, but {num_devices} devices are present"
            )
        return raw

    def axis_names(self) -> Tuple[str, ...]:
        return CANONICAL_AXES


def make_mesh(
    config: Optional[MeshConfig] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with the canonical axis names.

    Uses ``mesh_utils.create_device_mesh`` so the logical mesh respects the
    physical ICI torus (on CPU test backends it degrades to a reshape).
    """
    config = config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    sizes = config.sizes(len(devices))
    shape = tuple(sizes[a] for a in CANONICAL_AXES)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=np.asarray(devices))
    except (ValueError, AssertionError, NotImplementedError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, CANONICAL_AXES)


def batch_spec(extra_dims: int = 0) -> P:
    """PartitionSpec splitting dim 0 over every batch axis, rest replicated."""
    return P(BATCH_AXES, *([None] * extra_dims))


def batch_sharding(mesh: Mesh, extra_dims: int = 0) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(extra_dims))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def global_batch_divisor(mesh: Mesh) -> int:
    """How many ways the batch dimension is split on this mesh."""
    n = 1
    for a in BATCH_AXES:
        n *= mesh.shape[a]
    return n
