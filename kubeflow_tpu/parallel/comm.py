"""Analytic per-axis communication model for the composed 4D train step.

The multichip bench reports *estimated* per-device bytes moved over each
mesh axis per train step, derived from the PartitionSpecs and the schedule
shape — not measured from the interconnect. That is deliberate: the
estimate is platform-independent (works on the 8-virtual-device CPU CI
where there is no ICI to measure), and it is exactly the quantity you
diff when choosing a factorization or a gather mode before burning chips.

Ring-collective cost model (bytes on the wire per participating device,
buffer of B bytes over an axis of k devices):

    all_gather / reduce_scatter : (k-1)/k * B      (B = gathered size)
    all_reduce                  : 2*(k-1)/k * B    (reduce-scatter + gather)
    ppermute                    : B                (one neighbor hop)

Backward costs mirror forward (gather <-> reduce_scatter transpose, psum
-> psum), so fwd+bwd is 2x the forward count throughout. The model covers
the pipeline region's collectives — the dominant traffic; the GSPMD
embed/unembed edges are small at these vocab sizes and are noted, not
modeled.
"""

from __future__ import annotations

from typing import Dict

from jax.sharding import Mesh

from .composite import CompositeConfig
from .mesh import AXIS_DATA, AXIS_FSDP, AXIS_MODEL, AXIS_PIPE
from .pipeline import schedule_stats


def ring_allgather_bytes(buffer_bytes: float, axis_size: int) -> float:
    """Per-device wire bytes to all_gather (or reduce_scatter) a buffer of
    ``buffer_bytes`` GATHERED size over ``axis_size`` ring devices."""
    if axis_size <= 1:
        return 0.0
    return (axis_size - 1) / axis_size * buffer_bytes


def ring_allreduce_bytes(buffer_bytes: float, axis_size: int) -> float:
    """Per-device wire bytes for a ring all_reduce (psum) of ``buffer_bytes``."""
    return 2.0 * ring_allgather_bytes(buffer_bytes, axis_size)


def composite_param_count(cfg: CompositeConfig) -> int:
    """Logical (unsharded) parameter count of the composite GPT."""
    d, ff, nl = cfg.d_model, cfg.d_ff, cfg.n_layers
    per_layer = 3 * d * d + d * d + 2 * d * ff + 2 * d  # qkv + wo + mlp + lns
    return nl * per_layer + cfg.vocab_size * d


def composite_step_flops(cfg: CompositeConfig, tokens: int) -> float:
    """Estimated fwd+bwd FLOPs for one step over ``tokens`` tokens: the
    standard 6*N approximation plus the quadratic attention term."""
    n = composite_param_count(cfg)
    attn = 12 * cfg.n_layers * cfg.d_model * cfg.seq  # per token, fwd+bwd
    return float(tokens) * (6.0 * n + attn)


def composite_comm_bytes(
    cfg: CompositeConfig,
    mesh: Mesh,
    num_micro: int,
    microbatch: int,
    *,
    virtual_stages: int = 1,
    gather_mode: str = "eager",
    dtype_bytes: int = 4,
) -> Dict[str, float]:
    """Estimated per-device bytes per train step (fwd+bwd), keyed by mesh
    axis, for the composite GPT under the given schedule and gather mode.

    ``microbatch`` is the GLOBAL microbatch size (the bench's ``mb``); the
    per-device activation slice divides it by the batch axes.
    """
    dp = mesh.shape.get(AXIS_DATA, 1)
    fs = mesh.shape.get(AXIS_FSDP, 1)
    tp = mesh.shape.get(AXIS_MODEL, 1)
    pp = mesh.shape.get(AXIS_PIPE, 1)
    d, ff, nl = cfg.d_model, cfg.d_ff, cfg.n_layers
    lpc = nl // (pp * virtual_stages)  # layers per stage chunk
    mb_local = max(1, microbatch // (dp * fs))
    act_bytes = mb_local * cfg.seq * d * dtype_bytes

    stats = schedule_stats(num_micro, pp, virtual_stages)
    total_steps = int(stats["total_steps"])
    compute_steps = int(stats["compute_steps"])  # = V * M

    # pipe: one activation ppermute per scan step, fwd + transposed bwd.
    pipe = 2.0 * total_steps * act_bytes if pp > 1 else 0.0

    # fsdp: tiled all_gathers of the tp-local layer weights, transposing to
    # reduce_scatters in bwd. Call count depends on the gather mode:
    #   eager     — lpc layer-gathers per stage invocation, V*M invocations
    #   overlap   — same + one discarded clamped prefetch per invocation
    #   amortized — ALL V*lpc chunk layers once per step (stage_prepare)
    layer_w_bytes = (3 * d * d + d * d + 2 * d * ff) // tp * dtype_bytes
    if gather_mode == "amortized":
        layer_gathers = virtual_stages * lpc
    elif gather_mode == "overlap":
        layer_gathers = compute_steps * (lpc + 1)
    else:
        layer_gathers = compute_steps * lpc
    fsdp = 2.0 * layer_gathers * ring_allgather_bytes(layer_w_bytes, fs)

    # model: two psums of the activation per block (attn-out, mlp-out),
    # mirrored in bwd; blocks executed = compute_steps * lpc.
    model = (
        2.0 * compute_steps * lpc * 2.0 * ring_allreduce_bytes(act_bytes, tp)
    )

    # data: gradient all-reduce of the locally-held param shard over the
    # data axis (fsdp grads arrive pre-scattered via the transposed
    # gathers); the replicated-over-(data,fsdp) embed reduces over both.
    chunk_layers = virtual_stages * lpc  # layers resident per device
    stage_shard_bytes = (
        chunk_layers * (layer_w_bytes // max(1, fs) + 2 * d * dtype_bytes)
    )
    embed_bytes = cfg.vocab_size * d // tp * dtype_bytes
    data = ring_allreduce_bytes(stage_shard_bytes, dp) + ring_allreduce_bytes(
        embed_bytes, dp * fs
    )

    out = {"pipe": pipe, "fsdp": fsdp, "model": model, "data": data}
    out["total"] = sum(out.values())
    return out


def collective_wait_seconds(
    total_bytes: float, *, link_bandwidth_gbps: float = 100.0
) -> float:
    """Analytic lower bound on a step's collective-wait wall time: the
    modeled per-device wire bytes (``composite_comm_bytes(...)["total"]``)
    pushed over one ICI link at ``link_bandwidth_gbps``.

    The straggler plane's beacons use it as the *expected* collective-wait
    baseline when a workload has no measured ``collective_wait`` phase: a
    worker whose measured wait dwarfs this analytic floor is waiting on a
    peer, not on the wire.
    """
    if total_bytes <= 0.0 or link_bandwidth_gbps <= 0.0:
        return 0.0
    return float(total_bytes) / (link_bandwidth_gbps * 1e9 / 8.0)
