"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

Switch/top-k routing in the TPU-native style: dense dispatch/combine
einsums with *static* capacity (no dynamic shapes under jit — XLA tiles
them straight onto the MXU), expert FFN weights stacked [E, ...] and
sharded over the ``expert`` axis, expert inputs sharding-constrained to the
same axis so XLA inserts the all-to-all between data and expert layouts.
Load-balance auxiliary loss follows the Switch Transformer formulation.

The reference has no MoE/parallelism code at all (SURVEY.md §2.10); this
module is part of the in-workload compute path of the TPU-native build.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_EXPERT, BATCH_AXES


def _constrain(x: jax.Array, mesh: Optional[Mesh], spec: P) -> jax.Array:
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def top_k_routing(
    router_logits: jax.Array, num_experts: int, capacity: int, k: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Position-based top-k token->expert assignment with static capacity.

    router_logits: [tokens, E]. Returns (dispatch [tokens, E, C] one-hot,
    combine [tokens, E, C] gate-weighted, aux_loss scalar). Tokens beyond an
    expert's capacity are dropped (their combine weights are zero), the
    standard Switch behavior; earlier positions win, matching the
    sequential-priority formulation.
    """
    tokens = router_logits.shape[0]
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)

    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [tokens, k]
    # expert_mask[t, j, e] — token t's j-th choice is expert e.
    expert_mask = jax.nn.one_hot(gate_idx, num_experts, dtype=jnp.float32)

    # Position of each token in its chosen expert's queue, counting all
    # higher-priority (choice-major, then position) assignments.
    flat_mask = expert_mask.transpose(1, 0, 2).reshape(k * tokens, num_experts)
    pos_in_expert = jnp.cumsum(flat_mask, axis=0) - flat_mask  # [k*tokens, E]
    pos = (pos_in_expert * flat_mask).sum(-1).reshape(k, tokens).T.astype(jnp.int32)  # [tokens, k]
    keep = (pos < capacity) & (gate_vals > 0)

    # aux loss: mean fraction of tokens routed to e * mean router prob for e
    # (computed over first choices, Switch eq. 4), scaled by E.
    first_choice = expert_mask[:, 0, :]
    density = first_choice.mean(axis=0)
    density_proxy = probs.mean(axis=0)
    aux_loss = num_experts * jnp.sum(density * density_proxy)

    pos_oh = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity, dtype=jnp.float32)
    # dispatch[t, e, c] = token t occupies slot c of expert e.
    dispatch = jnp.einsum("tke,tkc->tec", expert_mask, pos_oh)
    combine = jnp.einsum("tke,tkc,tk->tec", expert_mask, pos_oh, gate_vals.astype(jnp.float32))
    return dispatch, combine, aux_loss


class MoEMlp(nn.Module):
    """Expert-parallel FFN block: route -> all-to-all -> expert MLP -> return.

    Drop-in for a dense transformer MLP ([..., d_model] -> [..., d_model]).
    Stacked expert kernels are named ``experts_wi``/``experts_wo`` so the
    sharding heuristic (parallel/sharding.py ``expert`` rule) places their
    leading dim on the ``expert`` mesh axis. Pass ``mesh`` to add activation
    sharding constraints; aux loss is sown under ``("losses", "moe_aux")``.
    """

    num_experts: int
    d_ff: int
    k: int = 2
    capacity_factor: float = 1.25
    mesh: Optional[Mesh] = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        orig_shape = x.shape
        d_model = x.shape[-1]
        x2 = x.reshape(-1, d_model)
        tokens = x2.shape[0]
        capacity = max(1, int(self.capacity_factor * self.k * tokens / self.num_experts))

        router = self.param(
            "router", nn.initializers.lecun_normal(), (d_model, self.num_experts), jnp.float32
        )
        logits = x2.astype(jnp.float32) @ router
        dispatch, combine, aux = top_k_routing(logits, self.num_experts, capacity, self.k)
        self.sow("losses", "moe_aux", aux)

        wi = self.param(
            "experts_wi",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (self.num_experts, d_model, self.d_ff),
            jnp.float32,
        )
        wo = self.param(
            "experts_wo",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (self.num_experts, self.d_ff, d_model),
            jnp.float32,
        )

        # [tokens, d] -> [E, C, d]: XLA lowers this resharding to all-to-all
        # when tokens are batch-sharded and expert tensors expert-sharded.
        expert_in = jnp.einsum("td,tec->ecd", x2.astype(self.dtype), dispatch.astype(self.dtype))
        expert_in = _constrain(expert_in, self.mesh, P(AXIS_EXPERT, None, None))
        h = jnp.einsum("ecd,edf->ecf", expert_in, wi.astype(self.dtype))
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h, wo.astype(self.dtype))
        out = _constrain(out, self.mesh, P(AXIS_EXPERT, None, None))
        y = jnp.einsum("ecd,tec->td", out, combine.astype(self.dtype))
        y = _constrain(y.reshape(orig_shape), self.mesh, P(BATCH_AXES, *([None] * (len(orig_shape) - 1))))
        return y.astype(x.dtype)
