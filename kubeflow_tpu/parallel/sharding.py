"""Logical-axis sharding rules: how parameter pytrees land on the mesh.

Models annotate parameters with *logical* axis names (``"embed"``,
``"mlp"``, ``"heads"``, ``"vocab"`` …); a :class:`LogicalRules` table maps
logical names to mesh axes. This decouples model code from parallelism
strategy: the same BERT runs pure-dp, fsdp, or 2-way tensor-parallel by
swapping rule tables, with XLA inserting the all-gathers/reduce-scatters.

The reference has no analog (workload-internal concern, SURVEY.md §2.10);
the design follows the public scaling-book recipe: pick a mesh, annotate
shardings, let XLA place collectives.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeflow_tpu.parallel.mesh import AXIS_EXPERT, AXIS_FSDP, AXIS_MODEL

MeshAxes = Union[str, Tuple[str, ...], None]


@dataclass(frozen=True)
class LogicalRules:
    """Mapping from logical axis name -> mesh axis (or None = replicate)."""

    rules: Tuple[Tuple[str, MeshAxes], ...]

    @classmethod
    def of(cls, **rules: MeshAxes) -> "LogicalRules":
        return cls(tuple(rules.items()))

    def mesh_axes(self, logical: str) -> MeshAxes:
        for name, axes in self.rules:
            if name == logical:
                return axes
        return None

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        return P(*(self.mesh_axes(a) if a else None for a in logical_axes))

    def extended(self, **overrides: MeshAxes) -> "LogicalRules":
        kept = tuple((n, a) for n, a in self.rules if n not in overrides)
        return LogicalRules(kept + tuple(overrides.items()))


#: Everything replicated — single chip or pure data parallelism.
REPLICATED_RULES = LogicalRules.of()

#: ZeRO-3: shard the largest parameter axis over the fsdp mesh axis.
FSDP_RULES = LogicalRules.of(
    embed=AXIS_FSDP,
    vocab=AXIS_FSDP,
    conv_out=AXIS_FSDP,
)

#: Megatron-style tensor parallelism for transformer blocks, composed with
#: fsdp on the embedding axis.
TENSOR_PARALLEL_RULES = LogicalRules.of(
    embed=AXIS_FSDP,
    vocab=AXIS_MODEL,
    heads=AXIS_MODEL,
    mlp=AXIS_MODEL,
    conv_out=AXIS_MODEL,
    expert=AXIS_EXPERT,
)


def logical_sharding(mesh: Mesh, rules: LogicalRules, logical_axes: Sequence[Optional[str]]) -> NamedSharding:
    return NamedSharding(mesh, rules.spec(logical_axes))


def _infer_logical_axes(path: Tuple[Any, ...], leaf: jax.Array) -> Tuple[Optional[str], ...]:
    """Heuristic logical axes for an unannotated parameter.

    Matches on the parameter's *owning module* name (the path component
    before flax's leaf name ``kernel``/``bias``/``embedding``/``scale``), so
    ``attention/out_proj/kernel`` is classified by ``out_proj``, not by the
    enclosing ``attention``. Convention matches kubeflow_tpu.models naming;
    biases and norms replicate.
    """
    names = [str(getattr(p, "key", getattr(p, "name", p))).lower() for p in path]
    leaf_name = names[-1] if names else ""
    parent = names[-2] if len(names) >= 2 else leaf_name
    rank = leaf.ndim
    if rank <= 1:
        return (None,) * rank
    if leaf_name == "embedding" or "embedding" in parent:
        return ("vocab", "embed") + (None,) * (rank - 2)
    if "conv" in parent and rank == 4:
        return (None, None, None, "conv_out")
    if any(k in parent for k in ("query", "key", "value", "qkv")):
        # DenseGeneral [embed, heads, head_dim] or Dense [embed, heads*dim]
        return ("embed", "heads", None) if rank == 3 else ("embed", "heads")
    if any(k in parent for k in ("out_proj", "wo", "down_proj", "o_proj")):
        # DenseGeneral [heads, head_dim, embed] or Dense [mlp, embed]
        return ("heads", None, "embed") if rank == 3 else ("mlp", "embed")
    if "expert" in parent and rank >= 3:
        # MoE stacked expert kernels [num_experts, in, out].
        return ("expert",) + (None,) * (rank - 1)
    if parent in ("router", "gate", "gating") or "router" in parent:
        # MoE router kernel [embed, num_experts]: tiny, and its output
        # feeds a per-token argmax/top-k — shard nothing. (The substring
        # "gate" alone must NOT land here: "gate_proj" is an MLP kernel.)
        return (None,) * rank
    if any(k in parent for k in ("mlp", "intermediate", "wi", "up_proj", "gate")):
        return (None,) * (rank - 1) + ("mlp",)
    if rank == 2:
        return ("embed", None)
    return (None,) * rank


def _divisible_spec(spec: P, shape: Tuple[int, ...], mesh: Mesh) -> P:
    """Drop mesh axes that don't divide the dimension (tiny embeddings etc.)."""
    fixed = []
    for dim, axes in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axes is None:
            fixed.append(None)
            continue
        axes_tuple = axes if isinstance(axes, tuple) else (axes,)
        size = 1
        for a in axes_tuple:
            size *= mesh.shape[a]
        fixed.append(axes if dim % size == 0 else None)
    return P(*fixed)


def shard_pytree(params: Any, mesh: Mesh, rules: LogicalRules) -> Any:
    """NamedShardings for a parameter pytree (heuristic logical axes)."""

    def leaf_sharding(path: Tuple[Any, ...], leaf: Any) -> NamedSharding:
        axes = _infer_logical_axes(path, leaf)
        spec = _divisible_spec(rules.spec(axes), tuple(leaf.shape), mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf_sharding, params)


def constrain(x: jax.Array, logical_axes: Sequence[Optional[str]], rules: LogicalRules) -> jax.Array:
    """``with_sharding_constraint`` by logical names, for use inside jit."""
    return jax.lax.with_sharding_constraint(x, rules.spec(logical_axes))
