"""Ring attention: exact long-context attention over the ``seq`` mesh axis.

Queries stay put; key/value blocks rotate around the ring of devices via
``lax.ppermute`` (one ICI hop per step, overlapping compute with transfer),
while an online-softmax accumulator keeps the result exact — attention over
sequences far larger than one chip's HBM, with per-device memory O(L/N).

The reference has no long-context machinery at all (SURVEY.md §5 — it
schedules pods); this is the in-workload half of "long-context is
first-class". Causal masking is computed from global positions derived from
the device's ring index, so block-skipping keeps the causal case ~2x cheap.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel._compat import pcast_varying, shard_map_unchecked
from kubeflow_tpu.parallel.mesh import AXIS_MODEL, AXIS_SEQ, BATCH_AXES

_NEG_BIG = -1e30


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    scale: Optional[float],
    vary_axes: tuple = (),
) -> jax.Array:
    """Per-device body. q/k/v: [batch, seq_local, heads, head_dim]."""
    orig_dtype = q.dtype
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)

    axis_size = lax.psum(1, axis_name)
    axis_idx = lax.axis_index(axis_name)

    q_pos = axis_idx * lq + jnp.arange(lq)  # global query positions

    # Accumulators in f32 regardless of input dtype (bf16-safe softmax).
    # pcast-to-varying marks them device-varying over the ring axis so the
    # fori_loop carry type stays fixed once ppermute'd blocks mix in.
    vary = vary_axes or (BATCH_AXES + (axis_name,))
    o = pcast_varying(jnp.zeros((b, h, lq, d), jnp.float32), vary)
    m = pcast_varying(jnp.full((b, h, lq), _NEG_BIG, jnp.float32), vary)
    l = pcast_varying(jnp.zeros((b, h, lq), jnp.float32), vary)

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        src = (axis_idx - i) % axis_size  # ring index this k/v block came from
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur, preferred_element_type=jnp.float32)
        s = s * scale
        if causal:
            k_pos = src * lk + jnp.arange(lk)
            mask = q_pos[:, None] >= k_pos[None, :]  # [lq, lk]
            s = jnp.where(mask[None, None], s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return o_new, m_new, l_new, k_nxt, v_nxt

    o, m, l, _, _ = lax.fori_loop(0, axis_size, step, (o, m, l, k, v))
    l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows (shouldn't occur) -> 0 output
    out = (o / l[..., None]).astype(orig_dtype)
    return jnp.einsum("bhqd->bqhd", out)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    axis_name: str = AXIS_SEQ,
) -> jax.Array:
    """Sequence-parallel attention over ``mesh``'s ``seq`` axis.

    Inputs are globally [batch, seq, heads, head_dim] with seq sharded over
    ``axis_name`` and batch over the batch axes; output matches q's layout.
    Works with seq axis size 1 (degrades to one local softmax pass).

    When the mesh has a non-trivial ``model`` axis the heads dimension is
    sharded over it too (heads are independent in attention), composing
    tensor parallelism with the ring; head count must then divide the axis.
    """
    model_size = mesh.shape.get(AXIS_MODEL, 1)
    heads = q.shape[2]
    head_axes = AXIS_MODEL if model_size > 1 and heads % model_size == 0 else None
    spec = P(BATCH_AXES, axis_name, head_axes, None)
    vary_axes = BATCH_AXES + (axis_name,) + ((head_axes,) if head_axes else ())
    fn = shard_map_unchecked(
        functools.partial(
            _ring_attention_local,
            axis_name=axis_name,
            causal=causal,
            scale=scale,
            vary_axes=vary_axes,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


def full_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Single-device exact reference (tests + short-sequence fast path)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        mask = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_BIG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype))
    return out.astype(q.dtype)
