"""TPU-native parallelism: device meshes, sharding rules, distributed init.

The reference delegates all distribution to workload pods (SURVEY.md §2.10 —
no in-tree DP/TP/PP/SP code; CUDA images imply NCCL). Here the workload side
is first-class: a canonical mesh axis vocabulary shared by every model and by
the control plane's topology math (``kubeflow_tpu.tpu.topology``), sharding
via ``jax.sharding`` + XLA collectives over ICI/DCN, ring attention for
sequence parallelism, microbatch-streaming pipeline parallelism, and
expert-parallel MoE.
"""

from kubeflow_tpu.parallel.mesh import (  # noqa: F401
    AXIS_DATA,
    AXIS_EXPERT,
    AXIS_FSDP,
    AXIS_MODEL,
    AXIS_PIPE,
    AXIS_SEQ,
    MeshConfig,
    batch_sharding,
    make_mesh,
    replicated,
)
from kubeflow_tpu.parallel.sharding import (  # noqa: F401
    LogicalRules,
    logical_sharding,
    shard_pytree,
)
from kubeflow_tpu.parallel.moe import MoEMlp, top_k_routing  # noqa: F401
from kubeflow_tpu.parallel.pipeline import (  # noqa: F401
    deinterleave_stage_params,
    interleave_stage_params,
    pipeline_apply,
    schedule_stats,
    stack_stage_params,
    stage_param_spec,
)
