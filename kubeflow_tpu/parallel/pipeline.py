"""Pipeline parallelism: stage-partitioned layers, microbatch streaming.

GPipe-style schedule expressed the TPU way: every pipeline stage is the
*same* SPMD program under ``shard_map`` over the ``pipe`` mesh axis; stage
weights live stacked with the stage dimension sharded over that axis, and
activations hop stage->stage+1 once per step via ``lax.ppermute`` (one ICI
hop). Autodiff through the forward schedule yields the reverse-order
backward schedule automatically — ``ppermute`` differentiates into the
inverse permutation — so there is no hand-written backward pipeline.

With M microbatches and S stages the loop runs M+S-1 steps; bubble fraction
(S-1)/(M+S-1) shrinks as M grows. Per-device parameter memory is 1/S of the
stacked stack, the usual reason to pick ``pipe`` over pure fsdp when layers
are deep and ICI hops are cheap.

The reference control plane has no in-tree parallelism (SURVEY.md §2.10);
this is part of the in-workload half of the TPU-native build.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from kubeflow_tpu.parallel.mesh import AXIS_PIPE


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack S per-stage pytrees into one pytree with a leading stage dim.

    The result is what :func:`pipeline_apply` consumes; shard its leading
    dim over the ``pipe`` mesh axis (``stage_param_spec``).
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_param_spec(leaf: jax.Array) -> P:
    """PartitionSpec for stacked stage params: stage dim over ``pipe``."""
    return P(AXIS_PIPE, *([None] * (leaf.ndim - 1)))


def _local_pipeline(
    params: Any,
    x: jax.Array,
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    axis_name: str,
) -> jax.Array:
    """Per-device body. params: stage-local (leading dim 1); x: [M, mb, ...]."""
    n_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    is_first = stage == 0
    is_last = stage == n_stages - 1
    params = jax.tree_util.tree_map(lambda p: p[0], params)
    num_micro = x.shape[0]
    total_steps = num_micro + n_stages - 1
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, t):
        recv, out = carry
        # Stage 0 reads microbatch t from the input stream (clamped index —
        # past-M reads feed bubble steps whose results are discarded);
        # later stages consume what the previous stage sent last step.
        x_t = lax.dynamic_index_in_dim(x, jnp.clip(t, 0, num_micro - 1), keepdims=False)
        inp = jnp.where(is_first, x_t, recv)
        y = stage_fn(params, inp)
        # Last stage banks microbatch t-(S-1) once the pipeline is full.
        out_idx = jnp.clip(t - (n_stages - 1), 0, num_micro - 1)
        bank = jnp.logical_and(is_last, t >= n_stages - 1)
        cur = lax.dynamic_index_in_dim(out, out_idx, keepdims=False)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(bank, y, cur), out_idx, axis=0
        )
        recv = lax.ppermute(y, axis_name, fwd_perm)
        return (recv, out), None

    probe = jax.eval_shape(stage_fn, params, x[0])
    out0 = jnp.zeros(x.shape[:1] + probe.shape, probe.dtype)
    recv0 = jnp.zeros(probe.shape, probe.dtype)
    (_, out), _ = lax.scan(step, (recv0, out0), jnp.arange(total_steps))
    # Results live on the last stage only; psum broadcasts them (every other
    # stage contributes zeros) so the caller sees a replicated [M, mb, ...].
    return lax.psum(jnp.where(is_last, out, jnp.zeros_like(out)), axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_PIPE,
    param_specs: Any = None,
    x_spec: P = P(),
    out_spec: P = P(),
) -> jax.Array:
    """Run x through S pipelined stages of ``stage_fn`` over ``mesh``.

    - ``stage_fn(params_i, h) -> h'`` — one stage; output shape/dtype must
      equal input (homogeneous inter-stage activations, the GPipe contract).
    - ``stage_params`` — pytree with leading stage dim S (see
      :func:`stack_stage_params`), sharded over ``axis_name``.
    - ``x`` — [num_microbatches, microbatch, ...] input stream, replicated
      over ``axis_name`` (batch axes may shard its microbatch dim).

    Composition with the other mesh axes (parallel/composite.py): pass
    ``param_specs`` to also shard weight dims over ``fsdp``/``model`` (the
    stage dim must stay on ``axis_name``), ``x_spec``/``out_spec`` to shard
    the microbatch dim over the batch axes; ``stage_fn`` then runs manual
    SPMD — it sees LOCAL shards and uses collectives (all_gather over fsdp,
    psum over model) itself, exactly like a Megatron block.

    Returns [num_microbatches, microbatch, ...] outputs, replicated over the
    pipe axis. Differentiable end-to-end.
    """
    if mesh.shape[axis_name] > x.shape[0]:
        raise ValueError(
            f"need at least as many microbatches as stages: "
            f"{x.shape[0]} microbatches < {mesh.shape[axis_name]} stages"
        )
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(stage_param_spec, stage_params)
    fn = shard_map(
        functools.partial(_local_pipeline, stage_fn=stage_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=out_spec,
        check_vma=False,
    )
    return fn(stage_params, x)
