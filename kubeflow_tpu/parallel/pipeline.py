"""Pipeline parallelism: stage-partitioned layers, microbatch streaming.

Two schedules, one implementation, expressed the TPU way: every pipeline
stage is the *same* SPMD program under ``shard_map`` over the ``pipe`` mesh
axis; stage weights live stacked with the stage dimension sharded over that
axis, and activations hop stage->stage+1 once per step via ``lax.ppermute``
(one ICI hop). Autodiff through the forward schedule yields the
reverse-order backward schedule automatically — ``ppermute`` differentiates
into the inverse permutation — so there is no hand-written backward
pipeline.

**GPipe** (``virtual_stages=1``): with M microbatches and S stages the loop
runs M+S-1 steps; bubble fraction (S-1)/(M+S-1) shrinks as M grows.

**Interleaved / virtual stages** (``virtual_stages=V>1``): each device owns
V round-robin chunks of the layer stack (global chunk g = v*S + d lives on
device d = g % S, so params stack to [S*V, ...] in device-major round-robin
order — see :func:`interleave_stage_params`). Each microbatch circulates
the ring V times; a circular buffer on stage 0 holds last-stage outputs
until their re-entry slot. The loop runs V*M + S - 1 steps of 1/V the
per-step work, cutting the bubble fraction to (S-1)/(V*M+S-1) — the
Megatron-LM interleaved schedule, at the cost of V-1 extra ring traversals
of activation traffic.

Both schedules need M >= S (the circular-buffer slot math is conflict-free
iff microbatches outnumber stages; M == S works, M < S raises). Bubble
steps re-read wrapped microbatches whose output is discarded;
``mask_bubbles=True`` (default) wraps the stage body in ``lax.cond`` so
those steps skip the FLOPs entirely — validity depends only on (t, pipe
coordinate), so collectives inside the stage over *other* mesh axes stay
uniform within their groups.

Per-device parameter memory is 1/S of the stacked stack, the usual reason
to pick ``pipe`` over pure fsdp when layers are deep and ICI hops are
cheap. The reference control plane has no in-tree parallelism
(SURVEY.md §2.10); this is part of the in-workload half of the TPU-native
build.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from kubeflow_tpu.parallel._compat import shard_map_unchecked
from kubeflow_tpu.parallel.mesh import AXIS_PIPE


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack S (or S*V) per-stage pytrees into one pytree with a leading
    stage dim, in natural order (row g holds chunk g).

    The result is what :func:`pipeline_apply` consumes; shard its leading
    dim over the ``pipe`` mesh axis (``stage_param_spec``). For
    ``virtual_stages > 1`` permute to device-major round-robin order first
    with :func:`interleave_stage_params`.
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage_params)


def stage_param_spec(leaf: jax.Array) -> P:
    """PartitionSpec for stacked stage params: stage dim over ``pipe``."""
    return P(AXIS_PIPE, *([None] * (leaf.ndim - 1)))


def _round_robin_perm(n_stages: int, virtual_stages: int) -> List[int]:
    """Row d*V+v of the interleaved layout holds natural chunk v*S+d."""
    return [v * n_stages + d for d in range(n_stages) for v in range(virtual_stages)]


def interleave_stage_params(stage_params: Any, n_stages: int, virtual_stages: int) -> Any:
    """Natural chunk order [S*V, ...] -> device-major round-robin layout.

    After this permutation, sharding the leading dim over ``pipe`` hands
    device d exactly its V chunks {d, S+d, 2S+d, ...} as local rows
    [0..V), which is what the interleaved schedule indexes by repeat r.
    Identity when ``virtual_stages == 1``.
    """
    perm = jnp.array(_round_robin_perm(n_stages, virtual_stages))
    return jax.tree_util.tree_map(lambda p: jnp.take(p, perm, axis=0), stage_params)


def deinterleave_stage_params(stage_params: Any, n_stages: int, virtual_stages: int) -> Any:
    """Inverse of :func:`interleave_stage_params` (back to natural order)."""
    perm = _round_robin_perm(n_stages, virtual_stages)
    inv = [0] * len(perm)
    for row, g in enumerate(perm):
        inv[g] = row
    inv_arr = jnp.array(inv)
    return jax.tree_util.tree_map(lambda p: jnp.take(p, inv_arr, axis=0), stage_params)


def schedule_stats(
    num_micro: int, n_stages: int, virtual_stages: int = 1
) -> Dict[str, float]:
    """Analytic schedule shape: step counts and bubble fraction.

    Each step does 1/virtual_stages of a GPipe step's work, so
    ``bubble_fraction`` (share of a device's step-time spent idle) is
    (S-1)/(V*M+S-1) and strictly drops as V grows; ``bubble_steps`` is the
    per-device idle step count S-1 in the schedule's own step units.
    """
    total = virtual_stages * num_micro + n_stages - 1
    bubble = n_stages - 1
    return {
        "total_steps": total,
        "compute_steps": virtual_stages * num_micro,
        "bubble_steps": bubble,
        "bubble_fraction": bubble / total,
    }


def _local_pipeline(
    params: Any,
    x: jax.Array,
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    axis_name: str,
    n_stages: int,
    virtual_stages: int,
    mask_bubbles: bool,
    stage_prepare: Optional[Callable[[Any], Any]],
) -> jax.Array:
    """Per-device body. params: stage-local (leading dim V, round-robin
    chunks); x: [M, mb, ...]. One unified loop covers both schedules; the
    GPipe path is the V==1 specialization (static chunk 0, no circular
    buffer) so it stays bit-for-bit what it was before virtual stages."""
    stage = lax.axis_index(axis_name)
    is_first = stage == 0
    is_last = stage == n_stages - 1
    if stage_prepare is not None:
        # Runs ONCE per train step, outside the time scan: prepared leaves
        # are scan constants, so their cotangents accumulate across all
        # V*M compute steps and transpose into ONE reduce_scatter per
        # weight instead of one per microbatch (no_sync-style).
        params = stage_prepare(params)
    V = virtual_stages
    num_micro = x.shape[0]
    total_steps = V * num_micro + n_stages - 1
    fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    if V == 1:
        chunk0 = jax.tree_util.tree_map(lambda p: p[0], params)

        def select_chunk(r):
            return chunk0
    else:

        def select_chunk(r):
            rr = jnp.clip(r, 0, V - 1)
            return jax.tree_util.tree_map(
                lambda p: lax.dynamic_index_in_dim(p, rr, keepdims=False), params
            )

    probe = jax.eval_shape(stage_fn, select_chunk(0), x[0])
    zeros_y = jnp.zeros(probe.shape, probe.dtype)

    def step(carry, t):
        recv, circ, out = carry
        # Device d's schedule position: repeat r of microbatch m, valid for
        # V*M of the total_steps. The ring hop means device d+1 at step t+1
        # sees the same (r, m) its upstream neighbor computed at step t.
        u = t - stage
        r = u // num_micro
        m = jnp.mod(u, num_micro)
        valid = jnp.logical_and(u >= 0, u < V * num_micro)
        if V > 1:
            # Bank what the last stage sent us: microbatch (t - S) mod M
            # finished its previous ring pass exactly in time to re-enter
            # stage 0 here (store-then-read keeps M == S hazard-free).
            circ = lax.dynamic_update_index_in_dim(
                circ, recv, jnp.mod(t - n_stages, num_micro), axis=0
            )
            circ_m = lax.dynamic_index_in_dim(circ, m, keepdims=False)
        x_m = lax.dynamic_index_in_dim(x, m, keepdims=False)
        if V > 1:
            first_in = jnp.where(r <= 0, x_m, circ_m)
        else:
            first_in = x_m
        inp = jnp.where(is_first, first_in, recv)
        p_t = select_chunk(r)
        if mask_bubbles:
            # Bubble steps would burn real FLOPs on discarded output; skip
            # them. `valid` is uniform across any collective group inside
            # stage_fn (those span non-pipe axes), so collectives stay
            # consistent; valid computations only ever consume
            # valid-produced values, so results are unchanged bit-for-bit.
            y = lax.cond(valid, lambda: stage_fn(p_t, inp), lambda: zeros_y)
        else:
            y = stage_fn(p_t, inp)
        # Last stage on the final repeat banks microbatch m's output.
        bank = jnp.logical_and(jnp.logical_and(is_last, valid), r == V - 1)
        cur = lax.dynamic_index_in_dim(out, m, keepdims=False)
        out = lax.dynamic_update_index_in_dim(out, jnp.where(bank, y, cur), m, axis=0)
        recv = lax.ppermute(y, axis_name, fwd_perm)
        return (recv, circ, out), None

    out0 = jnp.zeros(x.shape[:1] + probe.shape, probe.dtype)
    recv0 = jnp.zeros(probe.shape, probe.dtype)
    # The circular re-entry buffer only exists for V > 1; a scalar stands in
    # for it on the GPipe path so the carry structure stays uniform.
    circ0 = jnp.zeros(x.shape[:1] + probe.shape, probe.dtype) if V > 1 else jnp.zeros(())
    (_, _, out), _ = lax.scan(step, (recv0, circ0, out0), jnp.arange(total_steps))
    # Results live on the last stage only; psum broadcasts them (every other
    # stage contributes zeros) so the caller sees a replicated [M, mb, ...].
    return lax.psum(jnp.where(is_last, out, jnp.zeros_like(out)), axis_name)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    *,
    axis_name: str = AXIS_PIPE,
    param_specs: Any = None,
    x_spec: P = P(),
    out_spec: P = P(),
    virtual_stages: int = 1,
    mask_bubbles: bool = True,
    stage_prepare: Optional[Callable[[Any], Any]] = None,
) -> jax.Array:
    """Run x through S*V pipelined stage chunks of ``stage_fn`` over ``mesh``.

    - ``stage_fn(params_chunk, h) -> h'`` — one stage chunk; output
      shape/dtype must equal input (homogeneous inter-stage activations,
      the GPipe contract).
    - ``stage_params`` — pytree with leading stage dim S*V, sharded over
      ``axis_name``. For ``virtual_stages > 1`` the rows must be in
      device-major round-robin order (:func:`interleave_stage_params`) so
      each device's local rows [0..V) are its chunks {d, S+d, ...}.
    - ``x`` — [num_microbatches, microbatch, ...] input stream, replicated
      over ``axis_name`` (batch axes may shard its microbatch dim).
    - ``virtual_stages=V`` — interleaved schedule: V*M+S-1 steps of 1/V the
      work, bubble fraction (S-1)/(V*M+S-1). ``virtual_stages=1`` is GPipe
      and reproduces it exactly.
    - ``mask_bubbles`` — skip the stage body on bubble steps via
      ``lax.cond`` (numerically identical either way; saves the FLOPs).
    - ``stage_prepare(local_params) -> local_params`` — optional hook run
      once per call inside the shard_map, before the time scan, on the
      local [V, ...]-leading param tree. Use it to ``all_gather`` fsdp
      weight shards once per step instead of once per microbatch: the
      prepared tree is a scan constant, so the gathers' transposed
      reduce-scatters also run once, amortized across microbatches.

    Composition with the other mesh axes (parallel/composite.py): pass
    ``param_specs`` to also shard weight dims over ``fsdp``/``model`` (the
    stage dim must stay on ``axis_name``), ``x_spec``/``out_spec`` to shard
    the microbatch dim over the batch axes; ``stage_fn`` then runs manual
    SPMD — it sees LOCAL shards and uses collectives (all_gather over fsdp,
    psum over model) itself, exactly like a Megatron block.

    Returns [num_microbatches, microbatch, ...] outputs, replicated over the
    pipe axis. Differentiable end-to-end.
    """
    n_stages = mesh.shape[axis_name]
    if virtual_stages < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {virtual_stages}")
    if n_stages > x.shape[0]:
        raise ValueError(
            f"need at least as many microbatches as stages: "
            f"{x.shape[0]} microbatches < {n_stages} stages"
        )
    want = n_stages * virtual_stages
    for path, leaf in jax.tree_util.tree_flatten_with_path(stage_params)[0]:
        if leaf.shape[:1] != (want,):
            raise ValueError(
                f"stage_params leading dim must be n_stages*virtual_stages="
                f"{n_stages}*{virtual_stages}={want}; leaf "
                f"{jax.tree_util.keystr(path)} has shape {leaf.shape}"
            )
    if param_specs is None:
        param_specs = jax.tree_util.tree_map(stage_param_spec, stage_params)
    fn = shard_map_unchecked(
        functools.partial(
            _local_pipeline,
            stage_fn=stage_fn,
            axis_name=axis_name,
            n_stages=n_stages,
            virtual_stages=virtual_stages,
            mask_bubbles=mask_bubbles,
            stage_prepare=stage_prepare,
        ),
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=out_spec,
    )
    return fn(stage_params, x)
