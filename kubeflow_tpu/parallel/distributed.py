"""Multi-host bootstrap: from webhook-injected env to a live JAX cluster.

The admission webhook injects *identical* env on every pod of a slice
(deterministic injection — kubeflow_tpu/tpu/env.py; the reference rejects
conflicting env merges, admission-webhook/main.go:152-187). Per-worker
identity is therefore derived here at runtime from the StatefulSet ordinal
in the pod hostname (``<name>-3`` → process 3) — the same stable-DNS scheme
the reference culler relies on (notebook-controller/pkg/culler/culler.go:138-144).

DCN rendezvous goes through ``jax.distributed.initialize`` (worker 0 is the
coordinator); ICI within a slice needs no code — libtpu/XLA own it.
"""

from __future__ import annotations

import os
import re
import socket
from dataclasses import dataclass
from typing import Optional

import jax

from kubeflow_tpu.tpu.env import (
    ENV_COORDINATOR_ADDRESS,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
)

_ORDINAL_RE = re.compile(r"-(\d+)$")


@dataclass(frozen=True)
class WorkerIdentity:
    process_id: int
    num_processes: int
    coordinator_address: Optional[str]

    @property
    def is_coordinator(self) -> bool:
        return self.process_id == 0

    @property
    def is_distributed(self) -> bool:
        return self.num_processes > 1


def ordinal_from_hostname(hostname: Optional[str] = None) -> int:
    """StatefulSet ordinal from the pod hostname; 0 if not pod-shaped."""
    host = hostname if hostname is not None else socket.gethostname()
    m = _ORDINAL_RE.search(host.split(".")[0])
    return int(m.group(1)) if m else 0


def _int_env(var: str, raw: str) -> int:
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{var}={raw!r} is not an integer; fix the env injected on this "
            "pod (TPU PodDefault webhook output)"
        ) from None


def identity_from_env(environ: Optional[dict] = None, hostname: Optional[str] = None) -> WorkerIdentity:
    env = os.environ if environ is None else environ
    num = _int_env(ENV_NUM_PROCESSES, env.get(ENV_NUM_PROCESSES, "1"))
    if num <= 1:
        # Single-process: hostname ordinals are meaningless ('tpu-vm-1' is not
        # worker 1 of anything) — always process 0.
        return WorkerIdentity(process_id=0, num_processes=1, coordinator_address=None)
    explicit = env.get(ENV_PROCESS_ID)
    pid = (
        _int_env(ENV_PROCESS_ID, explicit)
        if explicit is not None
        else ordinal_from_hostname(hostname)
    )
    coord = env.get(ENV_COORDINATOR_ADDRESS)
    if pid >= num:
        raise ValueError(f"worker ordinal {pid} >= num_processes {num}")
    return WorkerIdentity(process_id=pid, num_processes=num, coordinator_address=coord)


_initialized = False


def reset_initialized_for_testing() -> None:
    """Forget that :func:`initialize` ran, so tests can exercise the
    bootstrap path more than once per process (with a stubbed
    ``jax.distributed.initialize``). Never call this in production — the
    underlying JAX cluster cannot actually be re-initialized."""
    global _initialized
    _initialized = False


def initialize(environ: Optional[dict] = None, hostname: Optional[str] = None) -> WorkerIdentity:
    """Idempotently join the JAX cluster described by the injected env.

    Single-process (no coordinator env, or num_processes == 1) is a no-op,
    so the same training script runs unchanged on one chip or a v5e-256.
    """
    global _initialized
    ident = identity_from_env(environ, hostname)
    if ident.is_distributed and not _initialized:
        if not ident.coordinator_address:
            raise RuntimeError(
                f"{ENV_NUM_PROCESSES}={ident.num_processes} but {ENV_COORDINATOR_ADDRESS} unset; "
                "was this pod admitted through the TPU PodDefault webhook?"
            )
        jax.distributed.initialize(
            coordinator_address=ident.coordinator_address,
            num_processes=ident.num_processes,
            process_id=ident.process_id,
        )
        _initialized = True
    return ident
