"""Composed 4D parallelism: one GPT train step over dp x fsdp x tp x pp.

VERDICT r3 #6: the per-axis dryrun phases proved each parallelism axis as an
island; this module composes them in ONE program on ONE mesh — the way a
real large-model job runs (Megatron/GSPMD-style):

- ``pipe``  — transformer layers split into GPipe stages
  (parallel/pipeline.py: shard_map + ppermute microbatch streaming),
- ``model`` — Megatron tensor parallelism INSIDE each stage, written as
  manual SPMD: column-split QKV/W1 (no comm), row-split WO/W2 followed by
  one ``psum`` over the ``model`` axis per sublayer,
- ``fsdp``  — ZeRO-3: weight shards live split over ``fsdp``; each stage
  ``all_gather``s a weight right before use, and autodiff transposes that
  gather into the gradient ``reduce_scatter``,
- ``data``/``fsdp`` — the microbatch dim of the input stream is sharded
  over both batch axes (mesh.BATCH_AXES); gradient all-reduce over them is
  placed by autodiff through the shard_map.

Embedding/unembedding run OUTSIDE the pipeline under ordinary GSPMD jit
(vocab sharded over ``model``), so the program also exercises the
shard_map <-> GSPMD boundary in both directions.

The reference has no in-tree parallelism at all (SURVEY.md §2.10); this is
the in-workload half of the TPU-native build. Checkpoint/resume across a
DIFFERENT mesh factorization is exercised in ``__graft_entry__``
(dryrun phase 5) via training/checkpoint.py's template-sharded restore.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXIS_FSDP, AXIS_MODEL, AXIS_PIPE, BATCH_AXES
from .pipeline import pipeline_apply


@dataclass(frozen=True)
class CompositeConfig:
    vocab_size: int = 256
    d_model: int = 32
    n_heads: int = 4
    d_ff: int = 64
    n_layers: int = 4  # must divide by mesh pipe size
    seq: int = 16


def _param_specs(cfg: CompositeConfig) -> Dict[str, Any]:
    """Stage-stacked weight specs. Stage dim on ``pipe``; Megatron column/
    row splits on ``model``; the remaining large dim sharded over ``fsdp``
    (ZeRO-3), gathered at use inside the stage body."""
    return {
        "ln1_scale": P(AXIS_PIPE, None, None),
        "ln2_scale": P(AXIS_PIPE, None, None),
        # [S, L, d, 3, d]: the qkv role dim is explicit and UNsharded — a
        # flat [d, 3d] column-shard would hand device 0 "all of q plus half
        # of k" and silently change the math between factorizations.
        "wqkv": P(AXIS_PIPE, None, AXIS_FSDP, None, AXIS_MODEL),
        "wo": P(AXIS_PIPE, None, AXIS_MODEL, AXIS_FSDP),    # [S, L, d/tp, d]
        "w1": P(AXIS_PIPE, None, AXIS_FSDP, AXIS_MODEL),    # [S, L, d, ff/tp]
        "w2": P(AXIS_PIPE, None, AXIS_MODEL, AXIS_FSDP),    # [S, L, ff/tp, d]
    }


def init_params(rng: jax.Array, cfg: CompositeConfig, mesh: Mesh) -> Dict[str, Any]:
    """Global (sharded) param pytree: embed + stacked per-stage blocks."""
    pp = mesh.shape[AXIS_PIPE]
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by pipe={pp}")
    lps = cfg.n_layers // pp
    d, ff = cfg.d_model, cfg.d_ff
    ks = jax.random.split(rng, 5)
    scale = d ** -0.5
    stages = {
        "ln1_scale": jnp.ones((pp, lps, d), jnp.float32),
        "ln2_scale": jnp.ones((pp, lps, d), jnp.float32),
        "wqkv": jax.random.normal(ks[0], (pp, lps, d, 3, d), jnp.float32) * scale,
        "wo": jax.random.normal(ks[1], (pp, lps, d, d), jnp.float32) * scale,
        "w1": jax.random.normal(ks[2], (pp, lps, d, ff), jnp.float32) * scale,
        "w2": jax.random.normal(ks[3], (pp, lps, ff, d), jnp.float32) * (ff ** -0.5),
    }
    specs = _param_specs(cfg)
    stages = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in stages.items()
    }
    embed = jax.device_put(
        jax.random.normal(ks[4], (cfg.vocab_size, d), jnp.float32) * scale,
        NamedSharding(mesh, P(AXIS_MODEL, None)),
    )
    return {"embed": embed, "stages": stages}


def param_shardings(cfg: CompositeConfig, mesh: Mesh) -> Dict[str, Any]:
    """NamedSharding tree matching :func:`init_params` — the checkpoint
    restore template for THIS mesh (cross-factorization resume)."""
    specs = _param_specs(cfg)
    return {
        "embed": NamedSharding(mesh, P(AXIS_MODEL, None)),
        "stages": {k: NamedSharding(mesh, s) for k, s in specs.items()},
    }


def _stage_fn(cfg: CompositeConfig, p: Dict[str, jax.Array], h: jax.Array) -> jax.Array:
    """One pipeline stage = lps transformer blocks, manual SPMD.

    ``p`` leaves are LOCAL shards [1, lps, ...] (stage dim stripped by the
    pipeline body caller); ``h`` is the local microbatch [mb_local, seq, d].
    """
    def block(h, layer):
        ln1, ln2, wqkv_l, wo_l, w1_l, w2_l = layer
        # fsdp: gather the weight shard right before use; grad transposes to
        # reduce_scatter (ZeRO-3). tiled=True concatenates along the dim.
        wqkv = lax.all_gather(wqkv_l, AXIS_FSDP, axis=0, tiled=True)   # [d, 3, d/tp]
        wo = lax.all_gather(wo_l, AXIS_FSDP, axis=1, tiled=True)       # [d/tp, d]
        w1 = lax.all_gather(w1_l, AXIS_FSDP, axis=0, tiled=True)       # [d, ff/tp]
        w2 = lax.all_gather(w2_l, AXIS_FSDP, axis=1, tiled=True)       # [ff/tp, d]

        def ln(x, scale):
            mu = x.mean(-1, keepdims=True)
            var = ((x - mu) ** 2).mean(-1, keepdims=True)
            return (x - mu) * jax.lax.rsqrt(var + 1e-5) * scale

        # attention: column-split QKV -> local heads; causal; row-split WO
        x = ln(h, ln1)
        qkv = jnp.einsum("bsd,drh->bsrh", x, wqkv)       # [mb, s, 3, d/tp]
        dl = qkv.shape[-1]                               # d/tp local width
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        hd = cfg.d_model // cfg.n_heads
        nh = dl // hd                                    # local heads
        mb, s, _ = q.shape
        q = q.reshape(mb, s, nh, hd).transpose(0, 2, 1, 3)
        k = k.reshape(mb, s, nh, hd).transpose(0, 2, 1, 3)
        v = v.reshape(mb, s, nh, hd).transpose(0, 2, 1, 3)
        scores = (q @ k.transpose(0, 1, 3, 2)) * (hd ** -0.5)
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -1e30)
        attn = jax.nn.softmax(scores, axis=-1) @ v       # [mb, nh, s, hd]
        attn = attn.transpose(0, 2, 1, 3).reshape(mb, s, dl)
        # row-split output proj: partial sums reduced over the model axis
        h = h + lax.psum(attn @ wo, AXIS_MODEL)
        # mlp: column-split W1 (no comm), row-split W2 (+psum)
        x = ln(h, ln2)
        h = h + lax.psum(jax.nn.gelu(x @ w1) @ w2, AXIS_MODEL)
        return h, None

    layers = (p["ln1_scale"], p["ln2_scale"], p["wqkv"], p["wo"], p["w1"], p["w2"])
    h, _ = lax.scan(block, h, layers)
    return h


def make_train_step(cfg: CompositeConfig, mesh: Mesh, lr: float = 0.1):
    """jit-able (params, ids[M, mb, seq]) -> (params, loss): one SGD step of
    next-token CE under the full dp x fsdp x tp x pp composition."""
    batch_spec = P(None, BATCH_AXES, None)  # [M, mb, seq]
    h_spec = P(None, BATCH_AXES, None, None)  # [M, mb, seq, d]
    specs = _param_specs(cfg)

    def loss_fn(params, ids):
        # GSPMD region: embedding lookup, vocab sharded over `model`
        h = jnp.take(params["embed"], ids, axis=0)  # [M, mb, s, d]
        h = pipeline_apply(
            lambda p, hh: _stage_fn(cfg, p, hh),
            params["stages"],
            h,
            mesh,
            param_specs={k: specs[k] for k in params["stages"]},
            x_spec=h_spec,
            out_spec=h_spec,
        )
        logits = h @ params["embed"].T  # [M, mb, s, vocab]
        targets = jnp.roll(ids, -1, axis=-1)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], axis=-1))

    def step(params, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    in_sharding = (param_shardings(cfg, mesh), NamedSharding(mesh, batch_spec))
    return jax.jit(step, in_shardings=in_sharding,
                   out_shardings=(in_sharding[0], NamedSharding(mesh, P())))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(None, BATCH_AXES, None))
