"""Composed 4D parallelism: one GPT train step over dp x fsdp x tp x pp.

VERDICT r3 #6: the per-axis dryrun phases proved each parallelism axis as an
island; this module composes them in ONE program on ONE mesh — the way a
real large-model job runs (Megatron/GSPMD-style):

- ``pipe``  — transformer layers split into pipeline stage chunks
  (parallel/pipeline.py: shard_map + ppermute microbatch streaming; GPipe
  or, with ``virtual_stages>1``, the interleaved schedule),
- ``model`` — Megatron tensor parallelism INSIDE each stage, written as
  manual SPMD: column-split QKV/W1 (no comm), row-split WO/W2 followed by
  one ``psum`` over the ``model`` axis per sublayer,
- ``fsdp``  — ZeRO-3: weight shards live split over ``fsdp``; gathers run
  in one of three modes (``gather_mode``):
    * ``"eager"``     — gather each weight right before use, once per layer
      per microbatch (the baseline; autodiff transposes each gather into a
      per-microbatch gradient ``reduce_scatter``),
    * ``"overlap"``   — the per-stage layer loop is a ``lax.scan`` with a
      double-buffered carry that prefetches layer i+1's ``all_gather``
      while layer i computes, hiding gather latency behind the matmuls,
    * ``"amortized"`` — all chunk weights gather ONCE per train step via
      the pipeline's ``stage_prepare`` hook; the gathered tree is a scan
      constant, so cotangents accumulate across microbatches and each
      weight sees ONE transposed reduce-scatter per step (no_sync-style,
      ~M x less fsdp traffic at peak-memory cost of the gathered chunk).
- ``data``/``fsdp`` — the microbatch dim of the input stream is sharded
  over both batch axes (mesh.BATCH_AXES); gradient all-reduce over them is
  placed by autodiff through the shard_map.

Embedding/unembedding run OUTSIDE the pipeline under ordinary GSPMD jit
(vocab sharded over ``model``), so the program also exercises the
shard_map <-> GSPMD boundary in both directions.

All gather modes and both schedules are numerically equivalent (same math,
different comm placement); tests/test_multichip.py asserts the parities.

The reference has no in-tree parallelism at all (SURVEY.md §2.10); this is
the in-workload half of the TPU-native build. Checkpoint/resume across a
DIFFERENT mesh factorization is exercised in ``__graft_entry__``
(dryrun phase 5) via training/checkpoint.py's template-sharded restore.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import AXIS_FSDP, AXIS_MODEL, AXIS_PIPE, BATCH_AXES
from .pipeline import deinterleave_stage_params, interleave_stage_params, pipeline_apply

GATHER_MODES = ("eager", "overlap", "amortized")


@dataclass(frozen=True)
class CompositeConfig:
    vocab_size: int = 256
    d_model: int = 32
    n_heads: int = 4
    d_ff: int = 64
    n_layers: int = 4  # must divide by mesh pipe size * virtual_stages
    seq: int = 16


def _param_specs(cfg: CompositeConfig) -> Dict[str, Any]:
    """Stage-stacked weight specs. Stage dim on ``pipe``; Megatron column/
    row splits on ``model``; the remaining large dim sharded over ``fsdp``
    (ZeRO-3), gathered at use inside the stage body."""
    return {
        "ln1_scale": P(AXIS_PIPE, None, None),
        "ln2_scale": P(AXIS_PIPE, None, None),
        # [S*V, L, d, 3, d]: the qkv role dim is explicit and UNsharded — a
        # flat [d, 3d] column-shard would hand device 0 "all of q plus half
        # of k" and silently change the math between factorizations.
        "wqkv": P(AXIS_PIPE, None, AXIS_FSDP, None, AXIS_MODEL),
        "wo": P(AXIS_PIPE, None, AXIS_MODEL, AXIS_FSDP),    # [S*V, L, d/tp, d]
        "w1": P(AXIS_PIPE, None, AXIS_FSDP, AXIS_MODEL),    # [S*V, L, d, ff/tp]
        "w2": P(AXIS_PIPE, None, AXIS_MODEL, AXIS_FSDP),    # [S*V, L, ff/tp, d]
    }


def init_params(
    rng: jax.Array, cfg: CompositeConfig, mesh: Mesh, *, virtual_stages: int = 1
) -> Dict[str, Any]:
    """Global (sharded) param pytree: embed + stacked per-chunk blocks.

    Weights are drawn in canonical per-layer shape [n_layers, ...] and then
    reshaped into pp*V chunks, so the logical model is IDENTICAL across
    every (pp, virtual_stages) factorization — the parity tests and
    cross-factorization checkpoint resume depend on that. For V > 1 the
    chunk rows are permuted to the device-major round-robin layout
    :func:`kubeflow_tpu.parallel.pipeline.pipeline_apply` expects.
    """
    pp = mesh.shape[AXIS_PIPE]
    chunks = pp * virtual_stages
    if cfg.n_layers % chunks:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by "
            f"pipe={pp} * virtual_stages={virtual_stages}"
        )
    lpc = cfg.n_layers // chunks  # layers per stage chunk
    d, ff, nl = cfg.d_model, cfg.d_ff, cfg.n_layers
    ks = jax.random.split(rng, 5)
    scale = d ** -0.5

    def chunked(w):
        return w.reshape((chunks, lpc) + w.shape[1:])

    stages = {
        "ln1_scale": jnp.ones((chunks, lpc, d), jnp.float32),
        "ln2_scale": jnp.ones((chunks, lpc, d), jnp.float32),
        "wqkv": chunked(jax.random.normal(ks[0], (nl, d, 3, d), jnp.float32) * scale),
        "wo": chunked(jax.random.normal(ks[1], (nl, d, d), jnp.float32) * scale),
        "w1": chunked(jax.random.normal(ks[2], (nl, d, ff), jnp.float32) * scale),
        "w2": chunked(jax.random.normal(ks[3], (nl, ff, d), jnp.float32) * (ff ** -0.5)),
    }
    if virtual_stages > 1:
        stages = interleave_stage_params(stages, pp, virtual_stages)
    specs = _param_specs(cfg)
    stages = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in stages.items()
    }
    embed = jax.device_put(
        jax.random.normal(ks[4], (cfg.vocab_size, d), jnp.float32) * scale,
        NamedSharding(mesh, P(AXIS_MODEL, None)),
    )
    return {"embed": embed, "stages": stages}


def canonical_params(
    params: Dict[str, Any], mesh: Mesh, *, virtual_stages: int = 1
) -> Dict[str, Any]:
    """Sharded stage tree -> canonical per-layer host arrays.

    Inverse of :func:`init_params`'s chunk+interleave: un-permutes the V>1
    round-robin layout and flattens [chunks, lpc, ...] back to
    [n_layers, ...]. The result is factorization-independent — the elastic
    checkpoint format (docs/ELASTICITY.md): a (pp=4, V=1) job saves here
    and a (pp=2, V=2) restart rebuilds its own chunking from it via
    :func:`params_from_canonical`.
    """
    pp = mesh.shape[AXIS_PIPE]
    stages = {
        k: np.asarray(jax.device_get(v)) for k, v in params["stages"].items()
    }
    if virtual_stages > 1:
        stages = jax.tree_util.tree_map(
            np.asarray, deinterleave_stage_params(stages, pp, virtual_stages)
        )
    stages = {k: v.reshape((-1,) + v.shape[2:]) for k, v in stages.items()}
    return {"embed": np.asarray(jax.device_get(params["embed"])), "stages": stages}


def params_from_canonical(
    canon: Dict[str, Any], cfg: CompositeConfig, mesh: Mesh, *, virtual_stages: int = 1
) -> Dict[str, Any]:
    """Canonical per-layer arrays -> the sharded stage tree for THIS mesh.

    Mirrors :func:`init_params`'s chunk/interleave/device_put exactly, so
    ``params_from_canonical(canonical_params(p, m1, V=a), cfg, m2, V=b)``
    is the same logical model on a different (pp, V) factorization.
    """
    pp = mesh.shape[AXIS_PIPE]
    chunks = pp * virtual_stages
    if cfg.n_layers % chunks:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by "
            f"pipe={pp} * virtual_stages={virtual_stages}"
        )
    lpc = cfg.n_layers // chunks
    stages = {}
    for k, v in canon["stages"].items():
        arr = jnp.asarray(v)
        stages[k] = arr.reshape((chunks, lpc) + arr.shape[1:])
    if virtual_stages > 1:
        stages = interleave_stage_params(stages, pp, virtual_stages)
    specs = _param_specs(cfg)
    stages = {
        k: jax.device_put(v, NamedSharding(mesh, specs[k])) for k, v in stages.items()
    }
    embed = jax.device_put(
        jnp.asarray(canon["embed"]), NamedSharding(mesh, P(AXIS_MODEL, None))
    )
    return {"embed": embed, "stages": stages}


def param_shardings(cfg: CompositeConfig, mesh: Mesh) -> Dict[str, Any]:
    """NamedSharding tree matching :func:`init_params` — the checkpoint
    restore template for THIS mesh (cross-factorization resume)."""
    specs = _param_specs(cfg)
    return {
        "embed": NamedSharding(mesh, P(AXIS_MODEL, None)),
        "stages": {k: NamedSharding(mesh, s) for k, s in specs.items()},
    }


def _gather_layer(wqkv_l, wo_l, w1_l, w2_l):
    """all_gather one layer's fsdp weight shards to full (tp-local) size.

    Autodiff transposes each tiled gather into a gradient reduce_scatter —
    the ZeRO-3 contract."""
    return (
        lax.all_gather(wqkv_l, AXIS_FSDP, axis=0, tiled=True),  # [d, 3, d/tp]
        lax.all_gather(wo_l, AXIS_FSDP, axis=1, tiled=True),    # [d/tp, d]
        lax.all_gather(w1_l, AXIS_FSDP, axis=0, tiled=True),    # [d, ff/tp]
        lax.all_gather(w2_l, AXIS_FSDP, axis=1, tiled=True),    # [ff/tp, d]
    )


def _block(cfg: CompositeConfig, h, ln1, ln2, wqkv, wo, w1, w2):
    """One transformer block, weights fully gathered over fsdp (still
    tp-local): Megatron column/row splits with one psum per sublayer."""

    def ln(x, scale):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * scale

    # attention: column-split QKV -> local heads; causal; row-split WO
    x = ln(h, ln1)
    qkv = jnp.einsum("bsd,drh->bsrh", x, wqkv)       # [mb, s, 3, d/tp]
    dl = qkv.shape[-1]                               # d/tp local width
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    hd = cfg.d_model // cfg.n_heads
    nh = dl // hd                                    # local heads
    mb, s, _ = q.shape
    q = q.reshape(mb, s, nh, hd).transpose(0, 2, 1, 3)
    k = k.reshape(mb, s, nh, hd).transpose(0, 2, 1, 3)
    v = v.reshape(mb, s, nh, hd).transpose(0, 2, 1, 3)
    scores = (q @ k.transpose(0, 1, 3, 2)) * (hd ** -0.5)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, scores, -1e30)
    attn = jax.nn.softmax(scores, axis=-1) @ v       # [mb, nh, s, hd]
    attn = attn.transpose(0, 2, 1, 3).reshape(mb, s, dl)
    # row-split output proj: partial sums reduced over the model axis
    h = h + lax.psum(attn @ wo, AXIS_MODEL)
    # mlp: column-split W1 (no comm), row-split W2 (+psum)
    x = ln(h, ln2)
    h = h + lax.psum(jax.nn.gelu(x @ w1) @ w2, AXIS_MODEL)
    return h


def _stage_fn(
    cfg: CompositeConfig,
    p: Dict[str, jax.Array],
    h: jax.Array,
    *,
    gather_mode: str = "eager",
) -> jax.Array:
    """One pipeline stage chunk = lpc transformer blocks, manual SPMD.

    ``p`` leaves are LOCAL shards [lpc, ...] (chunk dim already selected by
    the pipeline body); ``h`` is the local microbatch [mb_local, seq, d].
    ``gather_mode`` picks where the fsdp all_gathers run: per-layer at use
    ("eager"), prefetched one layer ahead in a double-buffered scan carry
    ("overlap"), or not at all because the caller pre-gathered via
    ``stage_prepare`` ("pregathered", the amortized path).
    """
    lns = (p["ln1_scale"], p["ln2_scale"])
    ws = (p["wqkv"], p["wo"], p["w1"], p["w2"])

    if gather_mode == "overlap":
        lpc = p["ln1_scale"].shape[0]

        def gather_at(i):
            return _gather_layer(
                *(lax.dynamic_index_in_dim(w, i, keepdims=False) for w in ws)
            )

        def body(carry, i):
            h, g = carry
            # Issue layer i+1's gathers BEFORE touching layer i's weights:
            # the collectives have no data dependence on the block compute,
            # so the compiler can run them concurrently (async collectives
            # on TPU), hiding gather latency behind the matmuls. The final
            # iteration prefetches a clamped duplicate that is discarded.
            g_next = gather_at(jnp.minimum(i + 1, lpc - 1))
            ln1, ln2 = (
                lax.dynamic_index_in_dim(s, i, keepdims=False) for s in lns
            )
            h = _block(cfg, h, ln1, ln2, *g)
            return (h, g_next), None

        (h, _), _ = lax.scan(body, (h, gather_at(0)), jnp.arange(lpc))
        return h

    def block(h, layer):
        ln1, ln2, wqkv_l, wo_l, w1_l, w2_l = layer
        if gather_mode == "pregathered":
            wqkv, wo, w1, w2 = wqkv_l, wo_l, w1_l, w2_l
        else:  # eager: gather the weight shard right before use (ZeRO-3)
            wqkv, wo, w1, w2 = _gather_layer(wqkv_l, wo_l, w1_l, w2_l)
        return _block(cfg, h, ln1, ln2, wqkv, wo, w1, w2), None

    h, _ = lax.scan(block, h, lns + ws)
    return h


def _stage_prepare_fn(p: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Amortized-mode hook: gather ALL chunk weights once per train step.

    Runs inside the pipeline's shard_map before the time scan, on local
    leaves [V, lpc, ...] — the fsdp-sharded axes sit one dim further right
    than in the per-layer gathers. The prepared tree is a scan constant:
    each weight's gradient reduce-scatter runs once per step instead of
    once per microbatch."""
    return {
        "ln1_scale": p["ln1_scale"],
        "ln2_scale": p["ln2_scale"],
        "wqkv": lax.all_gather(p["wqkv"], AXIS_FSDP, axis=2, tiled=True),
        "wo": lax.all_gather(p["wo"], AXIS_FSDP, axis=3, tiled=True),
        "w1": lax.all_gather(p["w1"], AXIS_FSDP, axis=2, tiled=True),
        "w2": lax.all_gather(p["w2"], AXIS_FSDP, axis=3, tiled=True),
    }


def make_train_step(
    cfg: CompositeConfig,
    mesh: Mesh,
    lr: float = 0.1,
    *,
    virtual_stages: int = 1,
    gather_mode: str = "eager",
    mask_bubbles: bool = True,
):
    """jit-able (params, ids[M, mb, seq]) -> (params, loss): one SGD step of
    next-token CE under the full dp x fsdp x tp x pp composition.

    ``virtual_stages``/``gather_mode``/``mask_bubbles`` pick the schedule
    and comm placement (see module docstring); every combination computes
    the same math. ``params`` must come from :func:`init_params` with the
    same ``virtual_stages``.
    """
    if gather_mode not in GATHER_MODES:
        raise ValueError(f"gather_mode must be one of {GATHER_MODES}, got {gather_mode!r}")
    batch_spec = P(None, BATCH_AXES, None)  # [M, mb, seq]
    h_spec = P(None, BATCH_AXES, None, None)  # [M, mb, seq, d]
    specs = _param_specs(cfg)
    inner_mode = "pregathered" if gather_mode == "amortized" else gather_mode
    stage_prepare = _stage_prepare_fn if gather_mode == "amortized" else None

    def loss_fn(params, ids):
        # GSPMD region: embedding lookup, vocab sharded over `model`
        h = jnp.take(params["embed"], ids, axis=0)  # [M, mb, s, d]
        h = pipeline_apply(
            lambda p, hh: _stage_fn(cfg, p, hh, gather_mode=inner_mode),
            params["stages"],
            h,
            mesh,
            param_specs={k: specs[k] for k in params["stages"]},
            x_spec=h_spec,
            out_spec=h_spec,
            virtual_stages=virtual_stages,
            mask_bubbles=mask_bubbles,
            stage_prepare=stage_prepare,
        )
        logits = h @ params["embed"].T  # [M, mb, s, vocab]
        targets = jnp.roll(ids, -1, axis=-1)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], axis=-1))

    def step(params, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids)
        params = jax.tree_util.tree_map(lambda p, g: p - lr * g, params, grads)
        return params, loss

    in_sharding = (param_shardings(cfg, mesh), NamedSharding(mesh, batch_spec))
    return jax.jit(step, in_shardings=in_sharding,
                   out_shardings=(in_sharding[0], NamedSharding(mesh, P())))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(None, BATCH_AXES, None))
