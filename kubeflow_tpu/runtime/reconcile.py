"""Create-or-update reconcile helpers with field-copy diffing.

The semantics mirror the reference's shared reconcilehelper
(components/common/reconcilehelper/util.go:18-219): create the desired
object if absent; otherwise copy only the fields a controller owns onto the
found object and update only when something changed — never clobbering
cluster-managed fields (the reference is explicit about preserving
``spec.clusterIP`` — util.go:182).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client


def _copy_meta_fields(desired: Dict[str, Any], found: Dict[str, Any]) -> bool:
    changed = False
    for field in ("labels", "annotations", "ownerReferences"):
        want = desired["metadata"].get(field)
        if want is not None and found["metadata"].get(field) != want:
            found["metadata"][field] = want
            changed = True
    return changed


def copy_statefulset_fields(desired: Dict[str, Any], found: Dict[str, Any]) -> bool:
    """reference: CopyStatefulSetFields (util.go:107-134)."""
    changed = _copy_meta_fields(desired, found)
    if found.get("spec", {}).get("replicas") != desired.get("spec", {}).get("replicas"):
        found.setdefault("spec", {})["replicas"] = desired["spec"].get("replicas")
        changed = True
    if found.get("spec", {}).get("template") != desired.get("spec", {}).get("template"):
        found.setdefault("spec", {})["template"] = desired["spec"]["template"]
        changed = True
    return changed


def copy_deployment_fields(desired: Dict[str, Any], found: Dict[str, Any]) -> bool:
    changed = _copy_meta_fields(desired, found)
    if found.get("spec") != desired.get("spec"):
        found["spec"] = desired["spec"]
        changed = True
    return changed


def copy_service_fields(desired: Dict[str, Any], found: Dict[str, Any]) -> bool:
    """Preserves clusterIP (reference: util.go:166-197)."""
    changed = _copy_meta_fields(desired, found)
    cluster_ip = found.get("spec", {}).get("clusterIP")
    if found.get("spec") != desired.get("spec"):
        preserved = desired["spec"].get("clusterIP", cluster_ip)
        if found.get("spec", {}) != {**desired["spec"], "clusterIP": preserved}:
            found["spec"] = dict(desired["spec"])
            if cluster_ip is not None:
                found["spec"]["clusterIP"] = cluster_ip
            changed = True
    return changed


def copy_spec_fields(desired: Dict[str, Any], found: Dict[str, Any]) -> bool:
    """Generic: controller owns metadata labels/annotations + whole spec
    (used for VirtualService & other unstructured — util.go:199-219)."""
    changed = _copy_meta_fields(desired, found)
    if found.get("spec") != desired.get("spec"):
        found["spec"] = desired["spec"]
        changed = True
    return changed


def copy_rolebinding_fields(desired: Dict[str, Any], found: Dict[str, Any]) -> bool:
    """RBAC objects carry top-level roleRef/subjects rather than a spec."""
    changed = _copy_meta_fields(desired, found)
    for field in ("roleRef", "subjects"):
        if field in desired and found.get(field) != desired[field]:
            found[field] = desired[field]
            changed = True
    return changed


_COPIERS = {
    "StatefulSet": copy_statefulset_fields,
    "Deployment": copy_deployment_fields,
    "Service": copy_service_fields,
    "RoleBinding": copy_rolebinding_fields,
    "ClusterRoleBinding": copy_rolebinding_fields,
}


def reconcile_object(
    client: Client, desired: Dict[str, Any], owner: Optional[Dict[str, Any]] = None
) -> Dict[str, Any]:
    """Create-or-update ``desired``; returns the live object."""
    if owner is not None:
        apimeta.set_owner_reference(desired, owner)
    found = client.get_opt(
        apimeta.api_version_of(desired),
        desired["kind"],
        apimeta.name_of(desired),
        apimeta.namespace_of(desired),
    )
    if found is None:
        return client.create(desired)
    copier = _COPIERS.get(desired["kind"], copy_spec_fields)
    if copier(desired, found):
        return client.update(found)
    return found
