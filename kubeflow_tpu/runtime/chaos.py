"""Fault injection for the control plane: seeded, deterministic chaos.

The elastic-training claim (docs/ELASTICITY.md) is only worth making if a
harness can break the cluster on purpose and watch training survive. This
module is that harness — four injectors matching the real failure modes of
a TPU pool, driven by a seeded schedule so CI runs are reproducible:

- ``kill_node``           — a host vanishes: the Node object is deleted and
  every pod bound to it flips to Failed with NO drain warning (the
  spot-VM-reclaim / hardware-death case);
- ``preempt_gang``        — protocol-faithful preemption: stamp the drain
  deadline annotation + ``TrainingPreempted`` Event on the gang's pods,
  then delete them once all live pods ack or the deadline passes (what
  scheduler/core.py does, minus needing a real higher-priority gang);
- ``drop_informer_watch`` — close an informer's watch stream mid-flight,
  forcing the relist/reconnect path (bumps
  ``informer_watch_reconnects_total``);
- ``delay_apiserver``     — hold the store's global lock for N seconds so
  every API call in the process stalls (etcd brown-out).

ISSUE 9 extends the harness to the SERVING fleet (pass ``fleet=``, an
EngineFleet or anything exposing ``live_handles()``):

- ``slow_replica``            — add ``param`` seconds of latency to every
  engine iteration of one replica for ``duration`` seconds (a thermally
  throttled / noisy-neighbor chip): deadlines expire, the fleet breaker
  opens;
- ``crash_replica_mid_decode`` — poison one replica's next engine
  iteration so it dies exactly like a device/RPC failure, in-flight
  futures failing with ``EngineClosed``;
- ``client_abandon``          — cancel up to ``param`` in-flight/queued
  requests on a replica (clients disconnecting mid-generation); the
  engine must reap the slots.

ISSUE 13 adds the tenant-abuse kind (pass ``apiserver_url=``):

- ``flood_apiserver`` — a noisy tenant: blast real LIST traffic over HTTP
  at the apiserver at ``param`` qps for ``duration`` seconds, tagged with
  ``target`` as the ``X-Flow-Client`` header so the priority-and-fairness
  gate (apiserver/fairness.py) classifies it. 429s are expected and
  counted, not errors — shedding the flood is the point.

ISSUE 16 adds process-level kinds for the multi-process HA harness (pass
``procs=``, a mapping of role name → subprocess.Popen or a zero-arg
callable returning one, so the harness can swap in restarted processes):

- ``kill9_apiserver``  — SIGKILL the apiserver process: no shutdown hook
  runs, the WAL's durable prefix is all that survives;
- ``kill9_scheduler``  — SIGKILL a scheduler replica (``target`` selects
  the procs key, default ``"scheduler"``); the standby must take over the
  Lease and finish the gang wave.

ISSUE 20 adds the training-worker kinds for the straggler plane (pass
``workers=``, a mapping of worker id → ``training.heartbeat.WorkerBeacon``;
with no mapping the process-global beacon registry is consulted):

- ``slow_worker``  — stretch one worker's per-step pacing by factor
  ``param`` for ``duration`` seconds (degraded host): the skew detector
  must flag it;
- ``wedge_worker`` — park one worker inside its beacon's ``_wedge_wait``
  frame (zero forward progress) until ``duration`` elapses or stop():
  the hang detector must verdict it, and the stack dump names the frame.

Both reset on ``stop()`` so a finished chaos run never leaves a worker
degraded. Every firing bumps ``chaos_faults_injected_total{kind}``.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from ..api import meta as apimeta
from .metrics import METRICS

LOG = logging.getLogger(__name__)

KINDS = ("kill_node", "preempt_gang", "drop_informer_watch", "delay_apiserver",
         "slow_replica", "crash_replica_mid_decode", "client_abandon",
         "flood_apiserver", "kill9_apiserver", "kill9_scheduler",
         "slow_worker", "wedge_worker")

#: chaos components stamp Events under this source
COMPONENT = "chaos-monkey"


@dataclass(frozen=True)
class Fault:
    """One scheduled failure: fire ``kind`` against ``target`` at ``at``
    seconds after the monkey starts. ``param`` is kind-specific: drain
    grace seconds for preempt_gang, stall seconds for delay_apiserver,
    per-iteration delay seconds for slow_replica, request count for
    client_abandon. ``duration`` bounds how long a persistent fault
    (slow_replica) stays applied; 0 = until the monkey stops."""

    at: float
    kind: str
    target: Optional[str] = None  # node | "ns/gang" | informer kind | replica
    param: float = 0.0
    duration: float = 0.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; expected one of {KINDS}")


class ChaosSchedule:
    """An ordered fault list. Build explicitly, or derive deterministically
    from a seed with :meth:`seeded` — the same (seed, spec) always yields
    the same schedule, which is what lets the elastic-e2e CI job inject
    chaos and still be a reproducible test."""

    def __init__(self, faults: Sequence[Fault]) -> None:
        self.faults: List[Fault] = sorted(faults, key=lambda f: f.at)

    @classmethod
    def seeded(
        cls,
        seed: int,
        n: int,
        duration: float,
        targets: Dict[str, Sequence[str]],
        param: Dict[str, float] = None,
    ) -> "ChaosSchedule":
        """``n`` faults uniformly over ``duration`` seconds, kinds drawn
        from ``targets``' keys, target drawn per kind."""
        rng = random.Random(seed)
        kinds = sorted(targets)
        faults = []
        for _ in range(n):
            kind = rng.choice(kinds)
            choices = list(targets[kind])
            faults.append(
                Fault(
                    at=rng.uniform(0.0, duration),
                    kind=kind,
                    target=rng.choice(choices) if choices else None,
                    param=(param or {}).get(kind, 0.0),
                )
            )
        return cls(faults)


class ChaosMonkey:
    """Fires a :class:`ChaosSchedule` against a live control plane.

    ``store`` is only needed for ``delay_apiserver``; ``informers`` (any
    iterable of SharedInformers) only for ``drop_informer_watch``. Faults
    whose dependencies are absent are logged and skipped, not errors — a
    schedule is reusable across harnesses of different completeness.
    """

    def __init__(
        self,
        client,
        schedule: ChaosSchedule,
        *,
        store=None,
        informers: Sequence[Any] = (),
        fleet: Any = None,
        apiserver_url: Optional[str] = None,
        procs: Optional[Dict[str, Any]] = None,
        workers: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._client = client
        self._schedule = schedule
        self._store = store
        self._informers = list(informers)
        #: EngineFleet (or anything with ``live_handles()``) — the target
        #: set for the serving fault kinds
        self._fleet = fleet
        #: base URL of a live apiserver — the target of flood_apiserver
        self._apiserver_url = apiserver_url.rstrip("/") if apiserver_url else None
        #: role name → Popen (or zero-arg callable returning one) for the
        #: process-level kill9 kinds
        self._procs = dict(procs or {})
        #: worker id → WorkerBeacon (training/heartbeat.py) — the target set
        #: for the straggler-plane kinds slow_worker / wedge_worker
        self._workers = dict(workers or {})
        #: (sent, rejected) tallies of completed floods, for harness asserts
        self.flood_stats: List[Dict[str, int]] = []
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        #: engines slowed by slow_replica, reset on stop() so a finished
        #: chaos run never leaves a replica degraded
        self._slowed: List[Any] = []
        #: worker beacons degraded by slow_worker/wedge_worker, likewise
        #: restored on stop()
        self._degraded_workers: List[Any] = []
        self.fired: List[Fault] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ChaosMonkey":
        t = threading.Thread(target=self._run, name="chaos-monkey", daemon=True)
        self._threads.append(t)
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for eng in self._slowed:
            eng.step_delay_s = 0.0
        for beacon in self._degraded_workers:
            beacon.slow_factor = 1.0
            beacon.release()
        for t in self._threads:
            t.join(timeout=5.0)

    def join(self, timeout: Optional[float] = None) -> None:
        for t in list(self._threads):
            t.join(timeout=timeout)

    def _run(self) -> None:
        t0 = time.monotonic()
        for fault in self._schedule.faults:
            delay = fault.at - (time.monotonic() - t0)
            if delay > 0 and self._stop.wait(delay):
                return
            if self._stop.is_set():
                return
            self.inject(fault)

    # -- injectors -----------------------------------------------------------
    def inject(self, fault: Fault) -> None:
        LOG.warning("chaos: injecting %s target=%s param=%s",
                    fault.kind, fault.target, fault.param)
        try:
            getattr(self, f"_{fault.kind}")(fault)
        except Exception as e:  # a failed injection must not kill the monkey
            LOG.warning("chaos: %s failed: %s", fault.kind, e)
            return
        METRICS.counter("chaos_faults_injected_total", kind=fault.kind).inc()
        self.fired.append(fault)

    def _kill_node(self, fault: Fault) -> None:
        """Hardware death: pods on the node fail with no warning, then the
        Node object disappears from the ledger's world."""
        node = fault.target
        for pod in self._client.list("v1", "Pod"):
            if (pod.get("spec") or {}).get("nodeName") != node:
                continue
            pod = dict(pod)
            pod["status"] = dict(pod.get("status") or {})
            pod["status"]["phase"] = "Failed"
            try:
                self._client.update_status(pod)
            except Exception:
                continue
        try:
            self._client.delete("v1", "Node", node)
        except Exception:
            pass

    def _preempt_gang(self, fault: Fault) -> None:
        """The drain protocol, chaos-issued: deadline annotation +
        TrainingPreempted Event now; deletion on ack or deadline (in a
        side thread so later faults stay on schedule)."""
        from ..scheduler.gang import (
            DRAIN_ACK_ANNOTATION,
            DRAIN_DEADLINE_ANNOTATION,
            POD_GROUP_LABEL,
        )

        ns, _, gang = (fault.target or "").partition("/")
        ns = ns or None
        grace = max(0.0, fault.param)
        deadline = time.time() + grace
        pods = self._client.list(
            "v1", "Pod", ns, label_selector={POD_GROUP_LABEL: gang}
        )
        names = [apimeta.name_of(p) for p in pods]
        for p in pods:
            self._client.patch(
                "v1", "Pod", apimeta.name_of(p),
                {"metadata": {"annotations": {
                    DRAIN_DEADLINE_ANNOTATION: f"{deadline:.3f}"}}},
                ns,
            )
            self._client.emit_event(
                p, "TrainingPreempted",
                f"chaos preemption: checkpoint within {grace:.1f}s "
                f"(deadline {deadline:.3f}) or be evicted",
                type_="Warning", component=COMPONENT,
            )

        def evict_when_ready():
            while not self._stop.is_set() and time.time() < deadline:
                live = acked = 0
                for name in names:
                    pod = self._client.get_opt("v1", "Pod", name, ns)
                    if pod is None:
                        continue
                    live += 1
                    if apimeta.annotations_of(pod).get(DRAIN_ACK_ANNOTATION):
                        acked += 1
                if live == 0 or acked == live:
                    break
                self._stop.wait(0.02)
            for name in names:
                try:
                    self._client.delete("v1", "Pod", name, ns)
                except Exception:
                    continue

        t = threading.Thread(target=evict_when_ready, name="chaos-evict", daemon=True)
        self._threads.append(t)
        t.start()

    def _drop_informer_watch(self, fault: Fault) -> None:
        """Sever the watch stream; the informer must relist + reconnect."""
        dropped = 0
        for inf in self._informers:
            if fault.target and getattr(inf, "kind", None) != fault.target:
                continue
            watcher = getattr(inf, "_watcher", None)
            if watcher is None:
                continue
            try:
                watcher.close()
                dropped += 1
            except Exception:
                continue
        if not dropped:
            raise RuntimeError(f"no informer watch to drop for {fault.target!r}")

    def _delay_apiserver(self, fault: Fault) -> None:
        """etcd brown-out: hold the store's global lock so every API call
        (and every informer watch delivery) stalls for ``param`` seconds."""
        if self._store is None:
            raise RuntimeError("delay_apiserver needs a store")
        seconds = max(0.0, fault.param)

        def hold():
            with self._store._lock:
                # interruptible sleep — stop() must not wait out the stall
                end = time.monotonic() + seconds
                while time.monotonic() < end and not self._stop.is_set():
                    time.sleep(min(0.02, end - time.monotonic()))

        t = threading.Thread(target=hold, name="chaos-apiserver-delay", daemon=True)
        self._threads.append(t)
        t.start()

    # -- process-level injectors ----------------------------------------------
    def _kill9_proc(self, key: str) -> None:
        """SIGKILL the process registered under ``key`` — no signal handler,
        no atexit, no graceful lease release: the crash the durable control
        plane must absorb. The entry may be a live Popen or a zero-arg
        callable resolving to one (harnesses that restart processes)."""
        import signal

        proc = self._procs.get(key)
        if proc is None:
            raise RuntimeError(f"no process registered for {key!r}")
        if callable(proc) and not hasattr(proc, "pid"):
            proc = proc()
        if proc is None or proc.poll() is not None:
            raise RuntimeError(f"process {key!r} is not running")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10.0)

    def _kill9_apiserver(self, fault: Fault) -> None:
        self._kill9_proc(fault.target or "apiserver")

    def _kill9_scheduler(self, fault: Fault) -> None:
        self._kill9_proc(fault.target or "scheduler")

    # -- serving injectors ---------------------------------------------------
    def _find_replica(self, target: Optional[str]):
        """Resolve ``target`` against the fleet's live replicas by gauge id
        or replica id; None picks the first live replica."""
        if self._fleet is None:
            raise RuntimeError("serving faults need a fleet")
        handles = list(self._fleet.live_handles())
        if not handles:
            raise RuntimeError("no live replica to target")
        if target is None:
            return handles[0]
        for h in handles:
            if target in (getattr(h, "gauge_id", None), getattr(h, "id", None)):
                return h
        raise RuntimeError(f"no live replica matches {target!r}")

    def _slow_replica(self, fault: Fault) -> None:
        """Thermal throttle / noisy neighbor: every engine iteration on the
        replica gains ``param`` seconds. Deadlines expire, the fleet marks
        the replica failing, its breaker opens; after ``duration`` seconds
        (or stop()) the replica recovers and the breaker re-closes."""
        eng = self._find_replica(fault.target).engine
        eng.step_delay_s = max(0.0, fault.param)
        self._slowed.append(eng)
        if fault.duration > 0:

            def recover():
                self._stop.wait(fault.duration)
                eng.step_delay_s = 0.0

            t = threading.Thread(target=recover, name="chaos-slow-recover", daemon=True)
            self._threads.append(t)
            t.start()

    # -- training-worker injectors --------------------------------------------
    def _find_worker(self, target: Optional[str]):
        """Resolve ``target`` against the registered worker beacons; None
        (with exactly one worker) picks it, else the target is required."""
        if not self._workers:
            # fall back to the process-global beacon registry so a harness
            # that built beacons after the monkey still resolves targets
            from ..training.heartbeat import beacons

            self._workers = beacons()
        if not self._workers:
            raise RuntimeError("worker faults need registered worker beacons")
        if target is None:
            if len(self._workers) == 1:
                return next(iter(self._workers.values()))
            raise RuntimeError("ambiguous worker target (several registered)")
        beacon = self._workers.get(target)
        if beacon is None:
            raise RuntimeError(f"no worker beacon named {target!r}")
        return beacon

    def _slow_worker(self, fault: Fault) -> None:
        """Degraded host / thermal throttle on one gang member: the worker's
        per-step pacing stretches by factor ``param`` (>1). Its peers stall
        in collectives behind it — the persistent-straggler signature the
        detector must flag. After ``duration`` seconds (or stop()) the
        worker recovers."""
        beacon = self._find_worker(fault.target)
        beacon.slow_factor = max(1.0, fault.param)
        self._degraded_workers.append(beacon)
        if fault.duration > 0:

            def recover():
                self._stop.wait(fault.duration)
                beacon.slow_factor = 1.0

            t = threading.Thread(target=recover, name="chaos-slow-worker-recover",
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def _wedge_worker(self, fault: Fault) -> None:
        """Hard wedge: the worker parks inside its beacon's ``_wedge_wait``
        frame at the next step and publishes nothing — zero forward
        progress, the hang the detector must verdict (and whose stack dump
        names this very frame). Released after ``duration`` seconds, or by
        stop(), or by the detector-driven eviction tearing the worker down."""
        beacon = self._find_worker(fault.target)
        beacon.wedge()
        self._degraded_workers.append(beacon)
        if fault.duration > 0:

            def release():
                self._stop.wait(fault.duration)
                beacon.release()

            t = threading.Thread(target=release, name="chaos-wedge-release",
                                 daemon=True)
            self._threads.append(t)
            t.start()

    def _crash_replica_mid_decode(self, fault: Fault) -> None:
        """Poison the replica's next engine iteration: it raises mid-decode
        exactly like a device/RPC failure, the engine shuts down, and every
        in-flight future fails with EngineClosed."""
        self._find_replica(fault.target).engine.fail_next_step = True

    def _client_abandon(self, fault: Fault) -> None:
        """Clients disconnect mid-generation: cancel up to ``param``
        in-flight/queued requests on the target replica (all replicas if
        the target has none). The engine must reap the freed slots."""
        want = max(1, int(fault.param or 1))
        if self._fleet is None:
            raise RuntimeError("serving faults need a fleet")
        handles = list(self._fleet.live_handles())
        if fault.target is not None:
            handles = [self._find_replica(fault.target)] + [
                h for h in handles
                if fault.target not in (getattr(h, "gauge_id", None),
                                        getattr(h, "id", None))
            ]
        cancelled = 0
        for h in handles:
            cancelled += h.engine.cancel_requests(want - cancelled)
            if cancelled >= want:
                break
        if cancelled == 0:
            raise RuntimeError("no in-flight request to abandon")

    # -- tenant-abuse injector -----------------------------------------------
    def flood_apiserver(self, flow: str, qps: float, duration_s: float,
                        wait: bool = False) -> Fault:
        """Convenience wrapper: inject a ``flood_apiserver`` fault NOW for
        ``duration_s`` seconds at ``qps`` LISTs/s under flow identity
        ``flow``. Returns the Fault; pass ``wait=True`` to block until the
        flood drains (harness synchronization)."""
        fault = Fault(at=0.0, kind="flood_apiserver", target=flow,
                      param=qps, duration=duration_s)
        self.inject(fault)
        if wait:
            self.join(timeout=duration_s + 10.0)
        return fault

    def _flood_apiserver(self, fault: Fault) -> None:
        """A noisy tenant: real HTTP LISTs against the apiserver at
        ``param`` qps for ``duration`` seconds, stamped with the flow
        identity so fairness classifies (and sheds) them. Runs in a side
        thread so scheduled faults stay on time; 429/503 responses are
        tallied, not raised — being shed is the expected outcome."""
        if self._apiserver_url is None:
            raise RuntimeError("flood_apiserver needs apiserver_url")
        import urllib.error
        import urllib.request

        base = self._apiserver_url
        flow = fault.target or "bulk:chaos"
        qps = max(0.1, fault.param)
        duration = max(0.0, fault.duration)
        stats = {"sent": 0, "rejected": 0, "errors": 0}
        stats_lock = threading.Lock()
        self.flood_stats.append(stats)
        # Burst-synchronized workers: every round, ALL workers fire at once
        # (thundering herd), then sleep to the next round boundary. A paced
        # open-loop flood never exceeds concurrency ~qps*latency, which
        # against a fast apiserver rounds to one — it would trickle through
        # the seats without ever pressing on the queues. Bursts are what a
        # real notebook-fanout tenant does and what the gate must shed.
        workers = max(4, min(16, int(qps / 25) or 4))
        interval = workers / qps  # rounds/s * workers = qps
        t0 = time.monotonic()
        end = t0 + duration

        def blast():
            k = 0
            while not self._stop.is_set():
                due = t0 + k * interval
                now = time.monotonic()
                if due >= end:
                    return
                if due > now and self._stop.wait(due - now):
                    return
                req = urllib.request.Request(
                    base + "/api/v1/pods", headers={"x-flow-client": flow})
                outcome = None
                try:
                    with urllib.request.urlopen(req, timeout=5.0) as resp:
                        resp.read()
                except urllib.error.HTTPError as e:
                    e.read()
                    outcome = "rejected" if e.code in (429, 503) else "errors"
                except Exception:
                    outcome = "errors"
                with stats_lock:
                    stats["sent"] += 1
                    if outcome:
                        stats[outcome] += 1
                # skip rounds that passed while this request was in flight —
                # the herd stays synchronized instead of smearing out
                k = max(k + 1, int((time.monotonic() - t0) / interval) + 1)

        for _ in range(workers):
            t = threading.Thread(target=blast, name="chaos-flood", daemon=True)
            self._threads.append(t)
            t.start()
