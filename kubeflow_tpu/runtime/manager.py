"""Controller runtime: watch-driven, level-triggered reconcile loops.

The execution model mirrors controller-runtime as the reference uses it
(notebook-controller/controllers/notebook_controller.go:573-670): a
reconciler registers the kind it is *for*, the kinds it *owns* (changes map
back to the controller owner), and arbitrary *watches* with mapping
functions. Events land in a deduplicating workqueue; one worker per
reconciler guarantees single-flight per key; failed reconciles requeue with
exponential backoff; ``Result(requeue_after=...)`` supports periodic work
(the culler's cadence — pkg/culler/culler.go:61-75).
"""

from __future__ import annotations

import heapq
import logging
import threading
import time
import traceback
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Store
from .metrics import METRICS
from .tracing import (
    TRACEPARENT_ANNOTATION,
    TRACER,
    format_traceparent,
    parse_traceparent,
)

log = logging.getLogger("kubeflow_tpu.runtime")


@dataclass(frozen=True)
class Request:
    namespace: Optional[str]
    name: str


@dataclass
class Result:
    requeue: bool = False
    requeue_after: float = 0.0


class Reconciler:
    """Subclass and override reconcile(); set FOR = (apiVersion, kind).

    ``cache`` is injected by the Manager: a shared InformerCache for
    watch-backed reads on hot paths (see runtime/informer.py). It is None
    when the reconciler runs outside a manager (unit tests) — fall back to
    direct client lists then.
    """

    cache = None  # set by Manager.add

    FOR: Tuple[str, str] = ("", "")
    OWNS: List[Tuple[str, str]] = []

    def reconcile(self, client: Client, req: Request) -> Result:  # pragma: no cover
        raise NotImplementedError

    def watches(self) -> List[Tuple[Tuple[str, str], Callable[[Dict[str, Any]], List[Request]]]]:
        """Extra (kind, mapper) watches beyond FOR/OWNS."""
        return []


class _Shard:
    """One lock domain of a sharded workqueue: its own pending dict (dedup),
    delayed heap, deadline/failure/enqueue-time maps."""

    __slots__ = ("lock", "pending", "delayed", "deadlines", "failures",
                 "added_at", "traces", "seq")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.pending: Dict[Request, None] = {}
        self.delayed: List[Tuple[float, int, Request]] = []
        #: authoritative earliest deadline per request — heap entries whose
        #: deadline disagrees are superseded duplicates and get dropped on pop
        self.deadlines: Dict[Request, float] = {}
        self.failures: Dict[Request, int] = {}
        #: enqueue time per pending request (queue-duration histogram)
        self.added_at: Dict[Request, float] = {}
        #: trace context per pending request (the Request key is frozen, so
        #: the causing event's traceparent rides beside it; last enqueuer
        #: wins — the dedup'd item parents to the freshest cause)
        self.traces: Dict[Request, str] = {}
        self.seq = 0


class _WorkQueue:
    """Deduplicating delayed workqueue, SHARDED by key hash.

    The round-11 churn profile showed every watch pump and the worker
    serializing on one queue-wide condition: at 100k-pod churn the producers
    (N watch streams mapping events to keys) convoy behind each other. Keys
    now hash to ``shards`` independent lock domains — dedup, delay heaps and
    failure counts are all per-shard, so two pumps enqueueing different keys
    never contend. One queue-wide condition remains solely as the consumer
    wakeup signal (producers touch it only to notify, never to do work
    under it); ``_version`` closes the scan-then-sleep lost-wakeup window.

    Instrumented with the controller-runtime workqueue metric family
    (``workqueue_depth``/``adds``/``queue_duration``/``retries``/
    ``unfinished_work``), labeled by the owning controller's name and
    AGGREGATED across shards — the dashboard contract is unchanged.
    """

    SHARDS = 8

    def __init__(self, name: str = "", shards: int = SHARDS) -> None:
        self.name = name
        self._cond = threading.Condition()
        self._shards = [_Shard() for _ in range(max(1, shards))]
        self._rr = 0  # consumer scan cursor: rotate so no shard starves
        self._version = 0  # bumped under _cond on every enqueue/shutdown
        self._processing = 0
        #: start times of in-flight items, FIFO-drained by task_done()
        self._inflight: Dict[int, float] = {}
        self._inflight_seq = 0
        #: traceparent captured at get() per in-flight request, consumed by
        #: trace_of() on the worker before it opens the reconcile span
        self._popped_traces: Dict[Request, str] = {}
        self._shutdown = False
        # unfinished-work must grow while a reconcile hangs, so it is
        # computed at scrape time; keyed registration keeps remounts (and
        # per-test Managers reusing controller names) from stacking up
        METRICS.register_collector(f"workqueue_{name}", self._collect)

    def _shard(self, req: Request) -> _Shard:
        return self._shards[hash(req) % len(self._shards)]

    def _depth(self) -> int:
        # len() per shard without locks: a point-in-time gauge may be off by
        # an in-flight add, never corrupt
        return sum(len(s.pending) for s in self._shards)

    @property
    def _delayed(self) -> List[Tuple[float, int, Request]]:
        # debug/test view of the delayed heaps, flattened across shards (a
        # request hashes to exactly one shard, so dedup invariants — one
        # heap entry per hot-requeued key — read the same as pre-sharding)
        return [entry for s in self._shards for entry in s.delayed]

    def _collect(self) -> None:
        now = time.monotonic()
        depth = self._depth()
        with self._cond:
            unfinished = sum(now - t for t in self._inflight.values())
        METRICS.gauge("workqueue_depth", queue=self.name).set(depth)
        METRICS.gauge("workqueue_unfinished_work_seconds", queue=self.name).set(unfinished)
        # backlog pressure in [0, 1): 0 when the worker keeps up (nothing
        # queued), -> 1 as keys pile up faster than the single worker
        # drains them — depth/(depth+workers) for this one-worker queue; a
        # busy worker with an empty queue is healthy, not saturated.
        METRICS.gauge("workqueue_saturation", queue=self.name).set(
            round(depth / (depth + 1.0), 6))

    def _wake(self) -> None:
        with self._cond:
            self._version += 1
            self._cond.notify()

    def add(self, req: Request, traceparent: Optional[str] = None) -> None:
        if traceparent is None:
            cur = TRACER.current_span()
            traceparent = format_traceparent(cur) if cur is not None else None
        sh = self._shard(req)
        with sh.lock:
            if traceparent:
                # last-enqueuer wins: a dedup'd key carries the trace of the
                # most recent event that (re)queued it, so the reconcile span
                # parents to the cause the worker is actually reacting to
                sh.traces[req] = traceparent
            if req in sh.pending:
                return
            sh.pending[req] = None
            sh.added_at.setdefault(req, time.monotonic())
        METRICS.counter("workqueue_adds_total", queue=self.name).inc()
        METRICS.gauge("workqueue_depth", queue=self.name).set(self._depth())
        self._wake()

    def add_after(self, req: Request, delay: float) -> None:
        deadline = time.monotonic() + delay
        sh = self._shard(req)
        with sh.lock:
            cur = sh.deadlines.get(req)
            if cur is not None and cur <= deadline:
                return  # already scheduled at least as early; no new entry
            sh.deadlines[req] = deadline
            sh.seq += 1
            heapq.heappush(sh.delayed, (deadline, sh.seq, req))
        self._wake()

    def add_rate_limited(self, req: Request) -> None:
        sh = self._shard(req)
        with sh.lock:
            n = sh.failures.get(req, 0)
            sh.failures[req] = n + 1
        METRICS.counter("workqueue_retries_total", queue=self.name).inc()
        self.add_after(req, min(0.005 * (2**n), 30.0))

    def forget(self, req: Request) -> None:
        sh = self._shard(req)
        with sh.lock:
            sh.failures.pop(req, None)

    def _try_pop(
        self, now: float
    ) -> Tuple[Optional[Request], Optional[str], Optional[float]]:
        """One pass over all shards from the rotation cursor: promote due
        delayed items, pop the first pending request. Returns (request or
        None, its carried traceparent or None, earliest future delayed
        deadline or None)."""
        n = len(self._shards)
        start = self._rr
        next_due: Optional[float] = None
        for i in range(n):
            sh = self._shards[(start + i) % n]
            with sh.lock:
                while sh.delayed and sh.delayed[0][0] <= now:
                    due, _, dreq = heapq.heappop(sh.delayed)
                    if sh.deadlines.get(dreq) != due:
                        continue  # superseded by an earlier add_after
                    del sh.deadlines[dreq]
                    if dreq not in sh.pending:
                        sh.pending[dreq] = None
                        sh.added_at.setdefault(dreq, now)
                        METRICS.counter("workqueue_adds_total", queue=self.name).inc()
                if sh.delayed:
                    due = sh.delayed[0][0]
                    next_due = due if next_due is None else min(next_due, due)
                if sh.pending:
                    req = next(iter(sh.pending))
                    del sh.pending[req]
                    added = sh.added_at.pop(req, None)
                    trace = sh.traces.pop(req, None)
                    if added is not None:
                        parsed = parse_traceparent(trace) if trace else None
                        # exemplar: a bad queue-duration bucket links straight
                        # to the trace of the event that sat in it
                        METRICS.histogram(
                            "workqueue_queue_duration_seconds", queue=self.name
                        ).observe(now - added,
                                  trace_id=parsed[0] if parsed else None)
                    self._rr = (start + i + 1) % n
                    return req, trace, next_due
        return None, None, next_due

    def get(self, timeout: Optional[float] = None) -> Optional[Request]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cond:
                v0 = self._version
            now = time.monotonic()
            req, trace, next_due = self._try_pop(now)
            if req is not None:
                with self._cond:
                    self._processing += 1
                    self._inflight_seq += 1
                    self._inflight[self._inflight_seq] = now
                    if trace:
                        self._popped_traces[req] = trace
                    else:
                        self._popped_traces.pop(req, None)
                METRICS.gauge("workqueue_depth", queue=self.name).set(self._depth())
                return req
            with self._cond:
                if self._shutdown:
                    return None
                if self._version != v0:
                    continue  # an add raced our scan; rescan before sleeping
                wait = None
                if next_due is not None:
                    wait = max(0.0, next_due - now)
                if deadline is not None:
                    rem = deadline - now
                    if rem <= 0:
                        return None
                    wait = rem if wait is None else min(wait, rem)
                self._cond.wait(wait)

    def trace_of(self, req: Request) -> Optional[str]:
        """The trace context carried by the last ``get()`` of this request
        (consumed — a second call returns None)."""
        with self._cond:
            return self._popped_traces.pop(req, None)

    def task_done(self) -> None:
        with self._cond:
            self._processing -= 1
            if self._inflight:
                del self._inflight[next(iter(self._inflight))]

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._version += 1
            self._cond.notify_all()

    def empty(self) -> bool:
        """Idle = nothing queued and nothing in flight. Delayed items
        (periodic requeues: culling cadence, scheduler retries) don't count —
        they represent scheduled future work, not outstanding work."""
        with self._cond:
            if self._processing != 0:
                return False
        for sh in self._shards:
            with sh.lock:
                if sh.pending:
                    return False
        return True


class _Controller:
    def __init__(self, mgr: "Manager", reconciler: Reconciler):
        self.mgr = mgr
        self.reconciler = reconciler
        self.name = type(reconciler).__name__
        self.queue = _WorkQueue(self.name)
        self._threads: List[threading.Thread] = []
        self._stopped = threading.Event()
        self._watchers: List[Any] = []
        self._watchers_lock = threading.Lock()

    def _map_owned(self, obj: Dict[str, Any]) -> List[Request]:
        for_api, for_kind = self.reconciler.FOR
        ref = apimeta.controller_owner_of(obj)
        if ref and ref.get("kind") == for_kind and ref.get("apiVersion") == for_api:
            return [Request(apimeta.namespace_of(obj), ref["name"])]
        return []

    def start(self) -> None:
        store = self.mgr.store
        for_api, for_kind = self.reconciler.FOR
        res = apimeta.REGISTRY.for_kind(for_api, for_kind)
        self._spawn_watch(store, res, lambda o: [Request(apimeta.namespace_of(o), apimeta.name_of(o))])
        for api_version, kind in self.reconciler.OWNS:
            owned = apimeta.REGISTRY.for_kind(api_version, kind)
            self._spawn_watch(store, owned, self._map_owned)
        for (api_version, kind), mapper in self.reconciler.watches():
            wres = apimeta.REGISTRY.for_kind(api_version, kind)
            self._spawn_watch(store, wres, mapper)
        t = threading.Thread(target=self._worker, name=f"{self.name}-worker", daemon=True)
        t.start()
        self._threads.append(t)

    def _spawn_watch(self, store: Store, res, mapper) -> None:
        def pump() -> None:
            # Re-watch loop: in-process watch streams are infinite, but a
            # remote stream ends on apiserver restart, idle socket timeout,
            # or a dropped slow watcher — without reconnection the controller
            # would go permanently deaf. Each (re)connect relists
            # (send_initial=True): level-triggered reconciles make replays
            # harmless, exactly like an informer resync.
            while not self._stopped.is_set():
                try:
                    watcher = store.watch(res, send_initial=True)
                except Exception:
                    log.warning("%s: watch connect failed for %s; retrying", self.name, res.plural)
                    self._stopped.wait(1.0)
                    continue
                with self._watchers_lock:
                    self._watchers.append(watcher)
                # Re-check after registration: stop() may have snapshotted
                # the watcher list between our loop check and the append —
                # without this, a freshly opened remote stream leaks.
                if self._stopped.is_set():
                    watcher.close()
                    with self._watchers_lock:
                        if watcher in self._watchers:
                            self._watchers.remove(watcher)
                    return
                try:
                    for event in watcher:
                        try:
                            # The object's creation traceparent (stamped by
                            # the apiserver) is the causing trace: carry it
                            # through the queue so the reconcile span joins
                            # the client call that made the object.
                            tp = apimeta.annotations_of(event.object).get(
                                TRACEPARENT_ANNOTATION)
                            for req in mapper(event.object) or []:
                                self.queue.add(req, traceparent=tp)
                        except Exception:  # mapper bugs must not kill the pump
                            log.exception("%s: watch mapper failed", self.name)
                finally:
                    with self._watchers_lock:
                        if watcher in self._watchers:
                            self._watchers.remove(watcher)
                if not self._stopped.is_set():
                    log.debug("%s: watch on %s ended; re-establishing", self.name, res.plural)
                    self._stopped.wait(0.2)

        t = threading.Thread(target=pump, name=f"{self.name}-watch-{res.plural}", daemon=True)
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stopped.set()
        with self._watchers_lock:
            watchers = list(self._watchers)
        for w in watchers:
            try:
                w.close()
            except Exception:
                pass
        self.queue.shutdown()
        # Join: a daemon thread still inside a ctypes call into the native
        # store when the interpreter finalizes gets pthread_exit()ed mid-C++
        # frame — glibc aborts with "FATAL: exception not rethrown".
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=2.0)

    def _worker(self) -> None:
        client = self.mgr.client
        while True:
            req = self.queue.get()
            if req is None:
                return
            start = time.monotonic()
            try:
                with TRACER.span(
                    "reconcile",
                    traceparent=self.queue.trace_of(req),
                    controller=self.name,
                    request=f"{req.namespace or ''}/{req.name}",
                ) as span:
                    result = self.reconciler.reconcile(client, req) or Result()
                    if result.requeue_after > 0:
                        span.set("requeue_after_s", result.requeue_after)
                self.queue.forget(req)
                if result.requeue_after > 0:
                    self.queue.add_after(req, result.requeue_after)
                elif result.requeue:
                    self.queue.add(req)
                METRICS.counter("controller_reconcile_total", controller=self.name, result="success").inc()
            except Exception:
                METRICS.counter("controller_reconcile_total", controller=self.name, result="error").inc()
                log.debug("%s: reconcile %s failed:\n%s", self.name, req, traceback.format_exc())
                self.queue.add_rate_limited(req)
            finally:
                self.queue.task_done()
                METRICS.histogram("controller_reconcile_seconds", controller=self.name).observe(
                    time.monotonic() - start
                )


class Manager:
    """Hosts controllers over one Store; runs the GC sweep the way
    kube-controller-manager would."""

    def __init__(self, store: Optional[Store] = None):
        from .informer import InformerCache  # late import: manager ↛ informer cycle

        self.store = store or Store()
        self.client = Client(self.store)
        self.cache = InformerCache(self.client)
        self._controllers: List[_Controller] = []
        self._started = False
        self._stop = threading.Event()

    def add(self, reconciler: Reconciler) -> "Manager":
        reconciler.cache = self.cache
        self._controllers.append(_Controller(self, reconciler))
        if self._started:
            self._controllers[-1].start()
        return self

    def start(self) -> "Manager":
        from .informer import InformerCache

        if self._started:
            return self
        if self._stop.is_set():
            # Restarting after stop() (a leader-election standby regaining
            # the lease): stopped controllers' queues and watch streams are
            # terminally shut down, so rebuild them around the same
            # reconcilers with a fresh stop event.
            self._stop = threading.Event()
            self._controllers = [_Controller(self, c.reconciler) for c in self._controllers]
            self.cache = InformerCache(self.client)
            for c in self._controllers:
                c.reconciler.cache = self.cache
        self._started = True
        for c in self._controllers:
            c.start()
        self._gc_thread = threading.Thread(target=self._gc_loop, name="garbage-collector", daemon=True)
        self._gc_thread.start()
        return self

    def _gc_loop(self) -> None:
        while not self._stop.wait(0.05):
            try:
                self.store.collect_garbage()
            except Exception:
                log.exception("gc sweep failed")

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        self._stop.set()
        for c in self._controllers:
            c.stop()
        self.cache.stop()
        gc_thread = getattr(self, "_gc_thread", None)
        if gc_thread is not None:
            gc_thread.join(timeout=2.0)

    def wait_idle(self, timeout: float = 10.0, settle: float = 0.15) -> bool:
        """Block until all queues drain and stay drained for ``settle`` seconds.

        Test helper — the envtest analog of 'eventually consistent'.
        """
        deadline = time.monotonic() + timeout
        quiet_since = None
        while time.monotonic() < deadline:
            if all(c.queue.empty() for c in self._controllers):
                if quiet_since is None:
                    quiet_since = time.monotonic()
                elif time.monotonic() - quiet_since >= settle:
                    return True
            else:
                quiet_since = None
            time.sleep(0.01)
        return False
