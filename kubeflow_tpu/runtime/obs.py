"""Mountable observability surface: ``/metrics`` + ``/debug/*`` routes.

Before this module nothing in the process actually *served* the
``MetricsRegistry.render()`` text or the Tracer's ring buffer; every role
re-implemented (or skipped) the plumbing. ``mount_observability(app)``
adds, idempotently, to any ``web.http.App``:

- ``GET /metrics``        — Prometheus/OpenMetrics text exposition (with
  trace-id exemplars on histogram buckets and the stdlib process collector),
- ``GET /debug/traces``   — recent spans as OTLP-shaped JSON, filterable by
  ``?trace_id=`` / ``?name=`` / ``?service=`` / ``?limit=`` (most recent
  last),
- ``GET /debug/vars``     — expvar-style process snapshot (pid, uptime,
  RSS, threads, GC, trace-buffer depth, metric families).

Mounted by the per-role ops server (runtime/bootstrap.py), the REST
apiserver, and the ModelServer, so the serving SLO histograms
(``serving_ttft_seconds`` and friends) and per-request traces are
scrapeable wherever the work runs.
"""

from __future__ import annotations

import collections
import gc
import os
import re
import sys
import threading
import time
import traceback
from typing import Any, Callable, Deque, Dict, List, Optional

from ..web.http import App, HttpError, JsonResponse, Request
from .metrics import (
    METRICS,
    MetricsRegistry,
    _PROCESS_START,
    _rss_bytes,
    install_process_collector,
)
from .tracing import TRACER, Tracer

#: exposition content type — OpenMetrics, since render() emits exemplar
#: suffixes and the ``# EOF`` terminator (a 0.0.4 content type would make
#: spec-compliant scrapers reject both)
EXPOSITION_CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: hard ceiling on one /debug/traces response (the ring holds 4096 spans)
MAX_TRACE_SPANS = 4096

#: named debug sources served at ``/debug/<name>`` — process-global so a
#: subsystem (the scheduler flight recorder) can register before or after
#: any particular app mounts observability; last registration wins, which
#: is what per-test reconciler instances need
_DEBUG_SOURCES: Dict[str, Callable[[Request], Any]] = {}


def register_debug_source(name: str, handler: Callable[[Request], Any]) -> None:
    """Expose ``handler(req) -> JSON-able`` at ``GET /debug/<name>`` on every
    app that mounts observability (the Go expvar/pprof publish pattern)."""
    _DEBUG_SOURCES[name] = handler


# -- /debug/stacks: all-thread stack dumps (the py-spy you always have) ------

#: bounded history of captured dumps, newest last — a hang verdict's
#: forensics must survive until an operator reads them, but an aggressive
#: detector must not grow host memory without limit
MAX_STACK_DUMPS = 32
_STACK_HISTORY: Deque[Dict[str, Any]] = collections.deque(maxlen=MAX_STACK_DUMPS)
_STACK_LOCK = threading.Lock()


def _thread_label(name: str) -> str:
    """Collapse digit runs (``worker-3`` → ``worker-N``) — same bounded-
    cardinality discipline as ``runtime_thread_crashes_total``."""
    return re.sub(r"\d+", "N", name or "unnamed")


def capture_stacks(reason: str = "manual") -> Dict[str, Any]:
    """Snapshot every live thread's Python stack via ``sys._current_frames``
    into the bounded dump ring, and return the dump. The straggler plane's
    hang forensics: the dump for a wedged worker names the exact frame the
    thread is parked in."""
    names = {t.ident: t.name for t in threading.enumerate()}
    threads: List[Dict[str, Any]] = []
    for ident, frame in sys._current_frames().items():
        stack = traceback.extract_stack(frame)
        threads.append({
            "thread": _thread_label(names.get(ident, "")),
            "threadName": names.get(ident, "unnamed"),
            "frames": [
                {"file": os.path.basename(f.filename), "line": f.lineno,
                 "function": f.name}
                for f in stack
            ],
            # innermost frame last in `frames`; surfaced for quick triage
            "current": stack[-1].name if stack else None,
        })
    dump = {
        "reason": reason,
        "capturedAt": time.time(),
        "pid": os.getpid(),
        "threadCount": len(threads),
        "threads": threads,
    }
    with _STACK_LOCK:
        _STACK_HISTORY.append(dump)
    return dump


def _stacks_source(req: Request) -> Dict[str, Any]:
    """``GET /debug/stacks`` — a fresh capture plus the bounded history
    (``?history=0`` suppresses it; ``?capture=0`` serves history only)."""
    capture = req.query1("capture", "1") != "0"
    want_history = req.query1("history", "1") != "0"
    live = capture_stacks(reason="debug-endpoint") if capture else None
    with _STACK_LOCK:
        history = list(_STACK_HISTORY) if want_history else []
    return {
        "live": live,
        "history": history,
        "maxDumps": MAX_STACK_DUMPS,
    }


register_debug_source("stacks", _stacks_source)


def otlp_traces(tracer: Tracer, trace_id: Optional[str] = None,
                name: Optional[str] = None, limit: int = 256,
                service: Optional[str] = None) -> dict:
    """The ring buffer's tail as one OTLP-shaped resourceSpans document —
    loadable by OTLP-adjacent tooling and by the e2e assertions. ``service``
    filters by each span's ``service.name`` attribute (a fleet replica's
    decode path federates under its engine's service identity)."""
    spans = tracer.finished_spans(name=name, trace_id=trace_id)
    if service is not None:
        spans = [s for s in spans
                 if s.attributes.get("service.name") == service]
    spans = spans[-max(0, min(limit, MAX_TRACE_SPANS)):]
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [
                        {"key": "service.name",
                         "value": {"stringValue": tracer.service}},
                        {"key": "service.instance.id",
                         "value": {"stringValue": tracer.instance}},
                    ]
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "kubeflow_tpu.runtime.tracing"},
                        "spans": [s.to_dict() for s in spans],
                    }
                ],
            }
        ]
    }


def mount_observability(
    app: App,
    registry: Optional[MetricsRegistry] = None,
    tracer: Optional[Tracer] = None,
) -> App:
    """Add the observability routes to ``app`` (no-op if already mounted)."""
    reg = registry if registry is not None else METRICS
    trc = tracer if tracer is not None else TRACER
    if any(pattern == "/metrics" for _m, pattern, _fn in app.iter_routes()):
        return app
    install_process_collector(reg)

    @app.route("/metrics")
    def metrics(req: Request) -> JsonResponse:
        return JsonResponse(
            reg.render(), headers={"Content-Type": EXPOSITION_CONTENT_TYPE}
        )

    @app.route("/debug/traces")
    def debug_traces(req: Request) -> dict:
        try:
            limit = int(req.query1("limit", "256"))
        except ValueError:
            raise HttpError(400, "limit must be an integer") from None
        return otlp_traces(
            trc,
            trace_id=req.query1("trace_id") or None,
            name=req.query1("name") or None,
            limit=limit,
            service=req.query1("service") or None,
        )

    @app.route("/debug/vars")
    def debug_vars(req: Request) -> dict:
        with reg._lock:
            families = len(reg._metrics)
        return {
            "pid": os.getpid(),
            "argv": sys.argv,
            "python_version": sys.version.split()[0],
            "uptime_seconds": round(time.time() - _PROCESS_START, 3),
            "resident_memory_bytes": _rss_bytes(),
            "threads": threading.active_count(),
            "gc": {str(i): s for i, s in enumerate(gc.get_stats())},
            "trace_buffer_spans": len(trc.finished_spans()),
            "metric_families": families,
            "app": app.name,
            "debug_sources": sorted(_DEBUG_SOURCES),
        }

    # Registered LAST: dispatch matches routes in registration order, so the
    # specific /debug/traces and /debug/vars patterns above always win over
    # this parameterized catch-all.
    @app.route("/debug/<source>")
    def debug_source(req: Request):
        handler = _DEBUG_SOURCES.get(req.params["source"])
        if handler is None:
            raise HttpError(
                404,
                f"unknown debug source {req.params['source']!r}; "
                f"registered: {sorted(_DEBUG_SOURCES)}",
            )
        return handler(req)

    return app
