"""Event pipeline: a K8s-style EventRecorder with correlation, spam
protection, and bounded retention.

Before this module every ``client.emit_event`` call created a fresh v1
Event object — a gang stuck in scheduling backoff would mint one Event per
attempt forever, and nothing ever deleted them. Kubernetes solved the same
problem in client-go's EventCorrelator (record/event.go +
events_cache.go): correlate duplicates onto one object, rate-limit noisy
sources, and let the apiserver GC old Events. The recorder rebuilds those
three layers over our Store:

- **Aggregation** — events are keyed on (involved uid, reason, component,
  type). A duplicate emit PATCHes the existing Event — bump ``count``,
  refresh ``lastTimestamp``/``message`` — instead of creating a new
  object, so "FailedScheduling × 40 attempts" is ONE Event with
  ``count=40``, exactly what ``kubectl describe`` renders.
- **Spam filter** — a token bucket per (component, involved uid), the
  shape of client-go's EventSourceObjectSpamFilter: ``burst`` emits up
  front, then ``refill_per_second``. Dropped emits are counted in
  ``events_discarded_total`` and return None; they must never block or
  fail the caller.
- **Retention GC** — the recorder remembers the Events it created in
  insertion order and deletes the oldest once more than ``max_events``
  correlation entries are live, bounding store growth from any single
  process regardless of uptime.

``Client.emit_event`` threads every existing call site (notebook
controller mirroring, culler, scheduler, webhooks) through one recorder
per client, so aggregation is platform-wide without touching callers.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from ..api import meta as apimeta
from .metrics import METRICS

#: correlation key: involved object identity + what happened + who said it
AggKey = Tuple[str, str, str, str]


@dataclass
class _AggEntry:
    namespace: str
    name: str  # Event object name in the store
    count: int
    #: spam bookkeeping rides the entry so both caches expire together
    first_seen: float = field(default_factory=time.monotonic)
    #: last emit that touched this key — evicting a recently-active entry
    #: means the cap, not natural quiescence, forced it out
    last_seen: float = field(default_factory=time.monotonic)


class _TokenBucket:
    def __init__(self, burst: int, refill_per_second: float) -> None:
        self.capacity = float(burst)
        self.tokens = float(burst)
        self.refill = refill_per_second
        self.last = time.monotonic()

    def take(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        self.tokens = min(self.capacity, self.tokens + (now - self.last) * self.refill)
        self.last = now
        if self.tokens < 1.0:
            return False
        self.tokens -= 1.0
        return True


def _involved_ref(involved: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "apiVersion": apimeta.api_version_of(involved),
        "kind": involved.get("kind"),
        "name": apimeta.name_of(involved),
        "namespace": apimeta.namespace_of(involved) or "default",
        "uid": apimeta.uid_of(involved),
    }


def _involved_id(involved: Dict[str, Any]) -> str:
    """Stable identity for correlation: uid when the object carries one,
    else the (kind, ns, name) triple — fixture objects in unit tests are
    often emitted before they ever hit the store."""
    uid = apimeta.uid_of(involved)
    if uid:
        return str(uid)
    ns = apimeta.namespace_of(involved) or "default"
    return f"{involved.get('kind')}/{ns}/{apimeta.name_of(involved)}"


class EventRecorder:
    """Correlating, spam-filtered, retention-bounded Event writer.

    One instance per :class:`~..apiserver.client.Client`; all methods are
    thread-safe (controllers emit from worker threads concurrently).
    """

    def __init__(
        self,
        client,
        max_events: int = 256,
        burst: int = 25,
        refill_per_second: float = 1.0 / 30.0,
        live_window_s: float = 60.0,
    ) -> None:
        self.client = client
        self.max_events = max_events
        self.burst = burst
        self.refill_per_second = refill_per_second
        #: an evicted entry emitted within this window counts as still-live
        #: (events_retention_saturated_total) — the cap is too small for
        #: the active set, not merely sweeping out dead history
        self.live_window_s = live_window_s
        self._lock = threading.Lock()
        #: insertion-ordered correlation cache — doubles as the GC ledger
        self._agg: Dict[AggKey, _AggEntry] = {}
        self._buckets: Dict[Tuple[str, str], _TokenBucket] = {}

    # -- the one public verb --------------------------------------------------
    def emit(
        self,
        involved: Dict[str, Any],
        reason: str,
        message: str,
        type_: str = "Normal",
        component: str = "kubeflow-tpu",
    ) -> Optional[Dict[str, Any]]:
        """Record an Event against ``involved``; returns the stored Event,
        or None when the source's spam budget dropped it."""
        key: AggKey = (_involved_id(involved), reason, component, type_)
        with self._lock:
            if not self._spam_ok(component, key[0]):
                METRICS.counter("events_discarded_total", component=component).inc()
                return None
            entry = self._agg.get(key)
        if entry is not None:
            ev = self._bump(key, entry, message, component)
            if ev is not None:
                return ev
            # the aggregated Event vanished under us (deleted externally);
            # fall through and start a fresh correlation entry
            with self._lock:
                self._agg.pop(key, None)
        ev = self._create(involved, reason, message, type_, component)
        doomed = []
        with self._lock:
            self._agg[key] = _AggEntry(
                namespace=ev["metadata"]["namespace"],
                name=ev["metadata"]["name"],
                count=1,
            )
            while len(self._agg) > self.max_events:
                old_key = next(iter(self._agg))
                doomed.append(self._agg.pop(old_key))
        now = time.monotonic()
        for old in doomed:  # retention GC: store deletes happen off-lock
            METRICS.counter("events_retention_deleted_total").inc()
            if now - old.last_seen < self.live_window_s:
                # the cap forced out a dedup key that was still taking
                # emits — its next duplicate will mint a brand-new Event
                # (count resets), so aggregation quality degrades; raise
                # max_events when this counter moves under load
                METRICS.counter("events_retention_saturated_total").inc()
            self.client.delete_opt("v1", "Event", old.name, old.namespace)
        return ev

    # -- internals -------------------------------------------------------------
    def _spam_ok(self, component: str, involved_id: str) -> bool:
        """Caller holds the lock. Per-(source, object) budget, the
        EventSourceObjectSpamFilter shape — one chatty pod cannot starve
        every other object's events from the same component."""
        bkey = (component, involved_id)
        bucket = self._buckets.get(bkey)
        if bucket is None:
            bucket = self._buckets[bkey] = _TokenBucket(self.burst, self.refill_per_second)
            # the bucket map tracks the agg cache's bound: drop stale buckets
            # once it outgrows the retention budget by a wide margin
            if len(self._buckets) > 4 * self.max_events:
                for stale in list(self._buckets)[: len(self._buckets) // 2]:
                    del self._buckets[stale]
        return bucket.take()

    def _bump(
        self, key: AggKey, entry: _AggEntry, message: str, component: str
    ) -> Optional[Dict[str, Any]]:
        """Aggregate a duplicate onto the existing Event via merge-patch."""
        from ..apiserver.store import NotFound, Store

        with self._lock:
            entry.count += 1
            entry.last_seen = time.monotonic()
            count = entry.count
        try:
            ev = self.client.patch(
                "v1",
                "Event",
                entry.name,
                {"count": count, "lastTimestamp": Store.now(), "message": message},
                entry.namespace,
            )
        except NotFound:
            return None
        METRICS.counter("events_emitted_total", component=component, outcome="aggregated").inc()
        return ev

    def _create(
        self,
        involved: Dict[str, Any],
        reason: str,
        message: str,
        type_: str,
        component: str,
    ) -> Dict[str, Any]:
        from ..apiserver.store import Store

        ns = apimeta.namespace_of(involved) or "default"
        ev = apimeta.new_object("v1", "Event", name="", namespace=ns)
        ev["metadata"]["generateName"] = f"{apimeta.name_of(involved)}."
        # ONE timestamp for both fields: calling Store.now() twice can
        # straddle a second boundary and mint a fresh Event whose
        # firstTimestamp != lastTimestamp (ISSUE 5 satellite).
        now = Store.now()
        ev.update(
            {
                "involvedObject": _involved_ref(involved),
                "reason": reason,
                "message": message,
                "type": type_,
                "source": {"component": component},
                "firstTimestamp": now,
                "lastTimestamp": now,
                "count": 1,
            }
        )
        created = self.client.create(ev)
        METRICS.counter("events_emitted_total", component=component, outcome="created").inc()
        return created

    # -- introspection (tests / debug) ----------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"correlated": len(self._agg), "buckets": len(self._buckets)}
