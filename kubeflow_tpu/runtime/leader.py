"""Leader election over coordination.k8s.io Lease objects.

Controller HA: the manifests run each controller role with >1 replica for
fast failover, but exactly one replica may reconcile at a time — two active
copies of a controller would fight over owned objects. The reference enables
this per binary via controller-runtime's leaderelection package
(notebook-controller/main.go:55-66, flags ``-enable-leader-election`` /
``-leader-election-namespace``); this is the same protocol re-implemented
against the platform apiserver:

- a Lease object per role (``spec.holderIdentity``, ``renewTime``,
  ``leaseDurationSeconds``, ``leaseTransitions``),
- the holder renews every ``renew_interval``; renewals and takeovers are
  optimistic-concurrency updates, so two candidates racing for an expired
  lease conflict on resourceVersion and exactly one wins,
- a standby acquires only after ``lease_duration`` passes without a renewal,
- a leader that cannot renew within ``lease_duration`` (apiserver partition,
  paused process) steps down and stops its manager — by the time the lease
  could have been taken over it is no longer reconciling (the Go
  implementation exits the process; stepping down to standby is equivalent
  under a Deployment, which would restart the exited pod into standby).

Wall-clock note: expiry is judged by each candidate's local reading of the
renewTime it last OBSERVED CHANGING, not by parsing the holder's timestamps
— the same trick client-go uses so leader election tolerates clock skew
between replicas.
"""

from __future__ import annotations

import logging
import socket
import threading
import time
import uuid
from typing import Callable, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import ApiError, Conflict, NotFound
from .metrics import METRICS

LEASE_API = "coordination.k8s.io/v1"

log = logging.getLogger("kubeflow_tpu.leader")


def default_identity() -> str:
    """hostname_uuid — unique per process, stable within it (client-go shape)."""
    return f"{socket.gethostname()}_{uuid.uuid4().hex[:8]}"


class LeaderElector:
    """Run callbacks while holding a named Lease.

    ``on_started_leading`` fires when the lease is acquired;
    ``on_stopped_leading`` fires when leadership is lost or released.
    Both run on the elector thread and must return promptly.
    """

    def __init__(
        self,
        client: Client,
        name: str,
        namespace: str = "kubeflow-system",
        identity: Optional[str] = None,
        lease_duration: float = 15.0,
        renew_interval: float = 2.0,
        retry_interval: float = 2.0,
        renew_deadline: Optional[float] = None,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        role: Optional[str] = None,
    ):
        if renew_interval >= lease_duration:
            raise ValueError("renew_interval must be < lease_duration")
        self.client = client
        self.name = name
        # Metric identity: bootstrap names leases "<role>-leader", so the
        # default recovers the role for the {role} label series.
        self.role = role or (name[: -len("-leader")] if name.endswith("-leader") else name)
        self.namespace = namespace
        self.identity = identity or default_identity()
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.retry_interval = retry_interval
        # A leader whose renewals stall past this (client-go renewDeadline,
        # default 2/3 of the lease) steps down BEFORE a standby could take
        # over — enforced by a watchdog thread because a renew hung inside
        # urlopen (RemoteStore timeout 30s > lease 15s) cannot observe its
        # own staleness.
        self.renew_deadline = renew_deadline or lease_duration * (2.0 / 3.0)
        if self.renew_deadline >= lease_duration:
            raise ValueError("renew_deadline must be < lease_duration")
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self._stop = threading.Event()
        self._leading = False
        self._lead_lock = threading.Lock()  # _set_leading from elector + watchdog
        self._thread: Optional[threading.Thread] = None
        self._watchdog_thread: Optional[threading.Thread] = None
        # Local-clock view of the observed lease: (holder, renewTime string)
        # and when WE saw that renewTime change. Expiry = no observed change
        # for lease_duration — immune to cross-replica clock skew.
        self._observed_record: Optional[tuple] = None
        self._observed_at = 0.0
        # When leading: last successful renew on OUR clock.
        self._last_renew = 0.0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "LeaderElector":
        # Register the standby state up front: a scraper must be able to
        # tell "standby" (0) from "not running an elector at all" (absent).
        METRICS.gauge("leader_election_state", role=self.role).set(0.0)
        self._thread = threading.Thread(
            target=self._run, name=f"leader-{self.name}", daemon=True
        )
        self._thread.start()
        self._watchdog_thread = threading.Thread(
            target=self._watchdog, name=f"leader-{self.name}-watchdog", daemon=True
        )
        self._watchdog_thread.start()
        return self

    def stop(self, release: bool = True) -> None:
        """Stop electing; optionally release the lease for instant failover."""
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self._watchdog_thread:
            self._watchdog_thread.join(timeout=5)
        if self._leading:
            self._set_leading(False)
            if release:
                self._release()

    @property
    def is_leader(self) -> bool:
        return self._leading

    # -- protocol ------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — URLError/OSError from a
                # RemoteStore partition must not kill the election loop: a
                # dead elector thread with _leading=True is permanent
                # split-brain once a standby takes over.
                log.warning("leader %s: apiserver unreachable: %s", self.name, e)
            self._stop.wait(self.renew_interval if self._leading else self.retry_interval)

    def _watchdog(self) -> None:
        """Step down when renewals stall past renew_deadline, even while the
        elector thread is stuck inside a hung request. If that hung renew
        later SUCCEEDS, optimistic concurrency guarantees the lease never
        changed hands meanwhile, so re-acquiring leadership is safe."""
        while not self._stop.is_set():
            if self._leading and time.monotonic() - self._last_renew > self.renew_deadline:
                log.warning(
                    "leader %s: no renewal for %.1fs (deadline %.1fs); stepping down",
                    self.name, time.monotonic() - self._last_renew, self.renew_deadline,
                )
                self._set_leading(False)
            self._stop.wait(self.renew_interval / 2.0)

    def _tick(self) -> None:
        lease = self.client.get_opt(LEASE_API, "Lease", self.name, self.namespace)
        now = time.monotonic()
        if lease is None:
            created = self._try(self._create_lease)
            if created is not None:
                self._won(created)
            elif self._leading:
                # Our lease was deleted externally and another candidate won
                # the re-create race: stop reconciling NOW, don't wait for
                # the next tick to observe the new holder.
                self._set_leading(False)
            return
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity")
        record = (holder, spec.get("renewTime"))
        if record != self._observed_record:
            self._observed_record = record
            self._observed_at = now

        if holder == self.identity:
            renewed = self._try(lambda: self._renew(lease))
            if renewed is not None:
                self._last_renew = now
                if not self._leading:
                    self._set_leading(True)
            elif self._leading and now - self._last_renew > self.lease_duration:
                self._set_leading(False)
            return

        # Someone else holds it. We must not be leading.
        if self._leading:
            self._set_leading(False)
        if holder and now - self._observed_at < self.lease_duration:
            return  # holder is live
        taken = self._try(lambda: self._take_over(lease))
        if taken is not None:
            self._won(taken)

    def _won(self, lease) -> None:
        self._observed_record = (self.identity, lease.get("spec", {}).get("renewTime"))
        self._observed_at = time.monotonic()
        self._last_renew = time.monotonic()
        self._set_leading(True)

    def _set_leading(self, leading: bool) -> None:
        with self._lead_lock:
            if leading == self._leading:
                return
            self._leading = leading
            METRICS.gauge("leader_is_leader", lease=self.name).set(1.0 if leading else 0.0)
            METRICS.gauge("leader_election_state", role=self.role).set(1.0 if leading else 0.0)
            if leading:
                METRICS.counter("leader_transitions_total", role=self.role).inc()
            log.info(
                "leader %s: %s (%s)",
                self.name,
                "acquired" if leading else "lost",
                self.identity,
            )
            cb = self.on_started_leading if leading else self.on_stopped_leading
            if cb:
                cb()

    @staticmethod
    def _try(fn):
        """Optimistic-concurrency attempt: Conflict/NotFound = lost the race."""
        try:
            return fn()
        except (Conflict, NotFound):
            return None

    # -- lease object manipulation ------------------------------------------
    def _lease_spec(self, transitions: int) -> dict:
        now = apimeta.now_rfc3339()
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration),
            "acquireTime": now,
            "renewTime": now,
            "leaseTransitions": transitions,
        }

    def _create_lease(self) -> dict:
        return self.client.create(
            apimeta.new_object(
                LEASE_API, "Lease", self.name, self.namespace,
                spec=self._lease_spec(transitions=0),
            )
        )

    def _renew(self, lease: dict) -> dict:
        lease = apimeta.deepcopy(lease)
        lease["spec"]["renewTime"] = apimeta.now_rfc3339()
        return self.client.update(lease)

    def _take_over(self, lease: dict) -> dict:
        lease = apimeta.deepcopy(lease)
        prev = lease["spec"].get("leaseTransitions", 0) or 0
        lease["spec"] = self._lease_spec(transitions=prev + 1)
        return self.client.update(lease)

    def _release(self) -> None:
        try:
            lease = self.client.get_opt(LEASE_API, "Lease", self.name, self.namespace)
            if lease and lease.get("spec", {}).get("holderIdentity") == self.identity:
                lease = apimeta.deepcopy(lease)
                lease["spec"]["holderIdentity"] = ""
                # Zero renewTime so a standby's freshness window doesn't
                # make it wait out the full lease_duration.
                lease["spec"]["renewTime"] = None
                self.client.update(lease)
        except ApiError:
            pass
