"""Per-role service bootstrap — what each deployed binary's main() does.

The manifests run one role per Deployment (``python -m
kubeflow_tpu.controllers.notebook`` etc. — the analog of the reference's
per-component Go binaries). Every role connects to the REST apiserver
(``APISERVER_URL``, default the in-cluster service DNS), serves /healthz +
Prometheus /metrics on ``METRICS_PORT``, and blocks until signalled.
"""

from __future__ import annotations

import logging
import os
import re
import signal
import threading
from typing import Optional

from ..apiserver.remote import RemoteStore
from ..web.http import App, Request
from .manager import Manager, Reconciler

DEFAULT_APISERVER = "http://apiserver:8001"

log = logging.getLogger("kubeflow_tpu.bootstrap")


def apiserver_url() -> str:
    return os.environ.get("APISERVER_URL", DEFAULT_APISERVER)


_NUM_RE = re.compile(r"\d+")


def _thread_label(name: str) -> str:
    """Collapse per-instance digits (``worker-3`` → ``worker-N``) so the
    thread label stays bounded-cardinality."""
    return _NUM_RE.sub("N", name or "unnamed")


def install_thread_excepthook() -> None:
    """Make silently-dying daemon threads observable.

    Every role runs its real work on daemon threads (manager loops,
    informers, elector, batcher); by default an uncaught exception there
    prints to stderr and the process keeps serving /healthz with its
    brain gone — the one failure mode static analysis (platlint) cannot
    see. Hook ``threading.excepthook`` to log the crash and increment
    ``runtime_thread_crashes_total{thread}`` so it alerts instead.

    Idempotent; chains to the previously-installed hook.
    """
    if getattr(threading.excepthook, "_kubeflow_tpu_hook", False):
        return
    from .metrics import METRICS

    prev = threading.excepthook

    def hook(args, /):
        if args.exc_type is SystemExit:
            return  # normal thread teardown, not a crash
        name = _thread_label(getattr(args.thread, "name", "") or "")
        try:
            METRICS.counter("runtime_thread_crashes_total", thread=name).inc()
        except Exception:  # noqa: BLE001 — the hook must never raise
            pass
        log.error(
            "thread %r crashed",
            getattr(args.thread, "name", "?"),
            exc_info=(args.exc_type, args.exc_value, args.exc_traceback),
        )
        # chain to a custom predecessor, but not the stock stderr printer —
        # the log.error above already carries the traceback
        if prev not in (None, threading.__excepthook__) and not getattr(
                prev, "_kubeflow_tpu_hook", False):
            try:
                prev(args)
            except Exception:  # noqa: BLE001 — a broken chained hook stays contained
                pass

    hook._kubeflow_tpu_hook = True
    threading.excepthook = hook


def connect(url: Optional[str] = None, timeout: float = 60.0) -> RemoteStore:
    store = RemoteStore(url or apiserver_url())
    store.wait_ready(timeout=timeout)
    return store


def serve_ops_endpoints(name: str, port: Optional[int] = None):
    """/healthz + observability server every role exposes (reference:
    promhttp on each Go binary — e.g. kfam routers.go:85-89; here the
    mount also brings /debug/traces + /debug/vars)."""
    from .obs import mount_observability
    from .tracing import TRACER

    # The process-global tracer takes the role's identity: federated spans
    # carry service.name=<role> so the TraceCollector can tell which
    # process each hop of an assembled trace ran in.
    TRACER.service = name

    app = App(f"{name}-ops")

    @app.route("/healthz")
    def healthz(req: Request):
        return {"status": "ok", "role": name}

    mount_observability(app)

    if port is None:
        port = int(os.environ.get("METRICS_PORT", "8080"))
    # 0.0.0.0: kubelet probes and Prometheus scrape via the pod IP.
    return app.serve(port, host="0.0.0.0")


def block_forever() -> None:
    """Park the main thread until SIGTERM/SIGINT (daemon threads do the work)."""
    stop = threading.Event()

    def handler(signum, frame):
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, handler)
        except ValueError:  # non-main thread (tests)
            break
    stop.wait()


def auth_from_env():
    """AuthConfig from the crud_backend env knob set (params.env wiring)."""
    from ..utils import env_flag
    from ..web.auth import AuthConfig

    return AuthConfig(
        userid_header=os.environ.get("USERID_HEADER", "kubeflow-userid"),
        userid_prefix=os.environ.get("USERID_PREFIX", ""),
        disable_auth=env_flag("APP_DISABLE_AUTH"),
        cluster_admins=[a for a in os.environ.get("CLUSTER_ADMIN", "").split(",") if a],
        secure_cookies=env_flag("APP_SECURE_COOKIES"),
        gateway_secret=os.environ.get("GATEWAY_SHARED_SECRET", ""),
    )


def run_webapp(name: str, factory, url: Optional[str] = None) -> None:
    """Standard web-app main: factory(client, auth) served on PORT."""
    from ..apiserver.client import Client

    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    install_thread_excepthook()
    store = connect(url)
    app = factory(Client(store), auth_from_env())
    server = ops = None
    try:
        server = app.serve(int(os.environ.get("PORT", "5000")), host="0.0.0.0")
        # Web apps expose /metrics + /healthz like every role (the
        # reference's KFAM serves promhttp on its API port,
        # routers.go:85-89). In-cluster each pod has its own netns, so the
        # shared 8080 default is fine; co-located host runs set
        # METRICS_PORT per process.
        try:
            ops = serve_ops_endpoints(name)
        except OSError as e:
            # Metrics exposure must not take the app down: co-located host
            # runs without METRICS_PORT collide on the shared 8080 default
            # (ADVICE r3). In-cluster each pod has its own netns, so this
            # only fires in dev/host layouts.
            log.warning("%s: ops endpoints unavailable (%s); serving without /metrics", name, e)
        log.info("%s serving on :%d (ops %s) against %s",
                 name, server.port, f":{ops.port}" if ops else "disabled", store.base_url)
        block_forever()
    finally:
        if server is not None:
            server.close()
        if ops is not None:
            ops.close()


def run_role(name: str, *reconcilers: Reconciler, url: Optional[str] = None) -> None:
    """Standard controller-role main: connect, reconcile, expose ops, block.

    With ``ENABLE_LEADER_ELECTION=true`` (reference flag
    ``-enable-leader-election``, notebook-controller/main.go:55-66) the
    manager only reconciles while holding the role's Lease in
    ``LEADER_ELECTION_NAMESPACE``; replicas > 1 give hot standbys.
    """
    from ..apiserver.client import Client
    from ..utils import env_flag
    from .leader import LeaderElector

    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    install_thread_excepthook()
    store = connect(url)
    mgr = Manager(store=store)
    for rec in reconcilers:
        mgr.add(rec)
    elector: Optional[LeaderElector] = None
    if env_flag("ENABLE_LEADER_ELECTION"):
        elector = LeaderElector(
            Client(store),
            name=f"{name}-leader",
            namespace=os.environ.get("LEADER_ELECTION_NAMESPACE", "kubeflow-system"),
            lease_duration=float(os.environ.get("LEASE_DURATION", "15")),
            renew_interval=float(os.environ.get("LEASE_RENEW_INTERVAL", "2")),
            on_started_leading=mgr.start,
            on_stopped_leading=mgr.stop,
        ).start()
    else:
        mgr.start()
    ops = None
    try:
        try:
            ops = serve_ops_endpoints(name)
        except OSError as e:
            # Same hardening as run_webapp (ADVICE r3): a port collision on
            # a co-located host must not crash a role whose manager/elector
            # threads are already running.
            log.warning("%s: ops endpoints unavailable (%s); running without /metrics",
                        name, e)
        log.info("%s running against %s (ops %s)", name, store.base_url,
                 f":{ops.port}" if ops else "disabled")
        block_forever()
    finally:
        if elector is not None:
            elector.stop()  # stops the manager via on_stopped_leading
        else:
            mgr.stop()
        if ops is not None:
            ops.close()
