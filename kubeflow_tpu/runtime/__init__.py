from .manager import Manager, Reconciler, Request, Result  # noqa: F401
from . import reconcile  # noqa: F401
from .metrics import MetricsRegistry, METRICS  # noqa: F401
