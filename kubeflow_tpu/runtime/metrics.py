"""Prometheus-style metrics registry with text exposition.

Every reference Go service exposes Prometheus counters/gauges (e.g.
notebook-controller/pkg/metrics/metrics.go:13-60, access-management
kfam/monitoring.go). This registry provides the same surface — counters,
gauges, histograms, label sets, ``/metrics`` text format — stdlib-only.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Tuple


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


class _Counter:
    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class _Gauge:
    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class _Histogram:
    BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0)

    def __init__(self) -> None:
        self.counts = [0] * (len(self.BUCKETS) + 1)
        self.sum = 0.0
        self.total = 0

    def observe(self, value: float) -> None:
        self.sum += value
        self.total += 1
        for i, b in enumerate(self.BUCKETS):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    @property
    def mean(self) -> float:
        return self.sum / self.total if self.total else 0.0


class NamespacedRegistry:
    """A registry view that prefixes every metric name with ``<prefix>_``.

    Subsystems register a namespace once (e.g. ``METRICS.namespace("scheduler")``)
    so all their series share a Prometheus-conventional prefix without each
    call site repeating it. Reads (``value``/``total``) resolve against the
    underlying registry, so tests can assert through either handle.
    """

    def __init__(self, registry: "MetricsRegistry", prefix: str) -> None:
        self._registry = registry
        self._prefix = prefix

    def _name(self, name: str) -> str:
        return f"{self._prefix}_{name}"

    def counter(self, name: str, **labels: str) -> _Counter:
        return self._registry.counter(self._name(name), **labels)

    def gauge(self, name: str, **labels: str) -> _Gauge:
        return self._registry.gauge(self._name(name), **labels)

    def histogram(self, name: str, **labels: str) -> _Histogram:
        return self._registry.histogram(self._name(name), **labels)

    def timer(self, name: str, **labels: str):
        return self._registry.timer(self._name(name), **labels)

    def total(self, name: str) -> float:
        return self._registry.total(self._name(name))

    def value(self, name: str, **labels: str) -> float:
        return self._registry.value(self._name(name), **labels)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Dict[Tuple[Tuple[str, str], ...], object]] = {}
        self._types: Dict[str, str] = {}

    def _get(self, name: str, kind: str, factory, labels: Dict[str, str]):
        with self._lock:
            if name in self._types and self._types[name] != kind:
                raise ValueError(f"metric {name} already registered as {self._types[name]}")
            self._types[name] = kind
            series = self._metrics.setdefault(name, {})
            key = _label_key(labels)
            if key not in series:
                series[key] = factory()
            return series[key]

    def counter(self, name: str, **labels: str) -> _Counter:
        return self._get(name, "counter", _Counter, labels)

    def gauge(self, name: str, **labels: str) -> _Gauge:
        return self._get(name, "gauge", _Gauge, labels)

    def histogram(self, name: str, **labels: str) -> _Histogram:
        return self._get(name, "histogram", _Histogram, labels)

    @contextmanager
    def timer(self, name: str, **labels: str):
        """Observe the wall time of a ``with`` body into histogram ``name``
        (the Prometheus *_seconds convention — StepClock and the serving
        paths time phases through this)."""
        hist = self.histogram(name, **labels)
        start = time.perf_counter()
        try:
            yield hist
        finally:
            hist.observe(time.perf_counter() - start)

    def total(self, name: str) -> float:
        """Sum of a counter/gauge across every label combination."""
        with self._lock:
            return sum(
                getattr(m, "value", 0.0) for m in self._metrics.get(name, {}).values()
            )

    def value(self, name: str, **labels: str) -> float:
        with self._lock:
            series = self._metrics.get(name, {})
            m = series.get(_label_key(labels))
            return getattr(m, "value", 0.0) if m else 0.0

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                kind = self._types[name]
                lines.append(f"# TYPE {name} {kind}")
                for key, m in sorted(self._metrics[name].items()):
                    label_str = ",".join(f'{k}="{v}"' for k, v in key)
                    suffix = f"{{{label_str}}}" if label_str else ""
                    if isinstance(m, _Histogram):
                        cum = 0
                        for i, b in enumerate(m.BUCKETS):
                            cum += m.counts[i]
                            le = ("," if label_str else "") + f'le="{b}"'
                            lines.append(f"{name}_bucket{{{label_str}{le}}} {cum}")
                        le = ("," if label_str else "") + 'le="+Inf"'
                        lines.append(f"{name}_bucket{{{label_str}{le}}} {m.total}")
                        lines.append(f"{name}_sum{suffix} {m.sum}")
                        lines.append(f"{name}_count{suffix} {m.total}")
                    else:
                        lines.append(f"{name}{suffix} {m.value}")
        return "\n".join(lines) + "\n"

    def namespace(self, prefix: str) -> NamespacedRegistry:
        return NamespacedRegistry(self, prefix)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._types.clear()


METRICS = MetricsRegistry()
