"""Prometheus-style metrics registry with text exposition.

Every reference Go service exposes Prometheus counters/gauges (e.g.
notebook-controller/pkg/metrics/metrics.go:13-60, access-management
kfam/monitoring.go). This registry provides the same surface — counters,
gauges, histograms, label sets, ``/metrics`` text format — stdlib-only.

Observability-plane extensions (docs/OBSERVABILITY.md):

- per-metric custom buckets: ``histogram(name, buckets=(...))`` — the fixed
  1ms–30s default ladder cannot resolve ms-scale inter-token latency,
- bucket-based quantile estimation: ``quantile(name, q)`` aggregates every
  label series of a histogram and linearly interpolates inside the bucket
  that holds the rank (the histogram_quantile() recipe, done in-process),
- OpenMetrics exemplars: each observation records the current span's trace
  id (or an explicitly passed one) against the bucket it landed in, and
  ``render()`` appends ``# {trace_id="..."} value ts`` to bucket lines,
- collectors: callbacks run at scrape time; ``install_process_collector``
  registers the stdlib process collector (RSS, threads, GC, CPU, uptime).
"""

from __future__ import annotations

import gc
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


_tracing = None


def _current_trace_id() -> Optional[str]:
    """Trace id of the calling thread's current span (exemplar source).
    Lazy module lookup: metrics must stay importable before tracing and
    add ~one getattr per observation when tracing is idle."""
    global _tracing
    if _tracing is None:
        # no import-time cycle: tracing reaches back here just as lazily
        # (the abandoned-span sweep's counter)
        from . import tracing as _t

        _tracing = _t
    span = getattr(_tracing._local, "span", None)
    return span.trace_id if span is not None else None


class _Counter:
    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class _Gauge:
    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class _Histogram:
    #: default ladder — serving SLO series override per metric
    BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0)

    def __init__(self, buckets: Optional[Sequence[float]] = None) -> None:
        self.buckets: Tuple[float, ...] = tuple(buckets) if buckets else self.BUCKETS
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.total = 0
        #: per-bucket exemplar: (observed value, trace_id, unix seconds)
        self.exemplars: List[Optional[Tuple[float, str, float]]] = [None] * (
            len(self.buckets) + 1
        )

    def _index(self, value: float) -> int:
        for i, b in enumerate(self.buckets):
            if value <= b:
                return i
        return len(self.buckets)

    def observe(self, value: float, count: int = 1,
                trace_id: Optional[str] = None) -> None:
        """Record ``count`` observations of ``value`` (count>1 amortizes a
        block of identical observations — the chunked decode path records
        per-token inter-token latency this way without per-token calls).
        The exemplar trace id defaults to the calling thread's current span
        so every histogram observation made under a span is correlatable."""
        self.sum += value * count
        self.total += count
        i = self._index(value)
        self.counts[i] += count
        if trace_id is None:
            trace_id = _current_trace_id()
        if trace_id is not None:
            self.exemplars[i] = (float(value), trace_id, time.time())

    @property
    def mean(self) -> float:
        return self.sum / self.total if self.total else 0.0


class NamespacedRegistry:
    """A registry view that prefixes every metric name with ``<prefix>_``.

    Subsystems register a namespace once (e.g. ``METRICS.namespace("scheduler")``)
    so all their series share a Prometheus-conventional prefix without each
    call site repeating it. Reads (``value``/``total``/``quantile``) resolve
    against the underlying registry, so tests can assert through either handle.
    """

    def __init__(self, registry: "MetricsRegistry", prefix: str) -> None:
        self._registry = registry
        self._prefix = prefix

    def _name(self, name: str) -> str:
        return f"{self._prefix}_{name}"

    def counter(self, name: str, **labels: str) -> _Counter:
        return self._registry.counter(self._name(name), **labels)

    def gauge(self, name: str, **labels: str) -> _Gauge:
        return self._registry.gauge(self._name(name), **labels)

    def histogram(self, name: str, buckets: Optional[Sequence[float]] = None,
                  **labels: str) -> _Histogram:
        return self._registry.histogram(self._name(name), buckets=buckets, **labels)

    def timer(self, name: str, **labels: str):
        return self._registry.timer(self._name(name), **labels)

    def total(self, name: str) -> float:
        return self._registry.total(self._name(name))

    def value(self, name: str, **labels: str) -> float:
        return self._registry.value(self._name(name), **labels)

    def quantile(self, name: str, q: float) -> Optional[float]:
        return self._registry.quantile(self._name(name), q)


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, Dict[Tuple[Tuple[str, str], ...], object]] = {}
        self._types: Dict[str, str] = {}
        #: first-registration bucket ladder per histogram name — every label
        #: series of a name shares one ladder or the exposition is corrupt
        self._hist_buckets: Dict[str, Tuple[float, ...]] = {}
        #: scrape-time callbacks (process collector etc.), keyed for idempotence;
        #: collectors survive reset() — they repopulate on the next render
        self._collectors: Dict[str, Callable[[], None]] = {}

    def _get(self, name: str, kind: str, factory, labels: Dict[str, str]):
        with self._lock:
            if name in self._types and self._types[name] != kind:
                raise ValueError(f"metric {name} already registered as {self._types[name]}")
            self._types[name] = kind
            series = self._metrics.setdefault(name, {})
            key = _label_key(labels)
            if key not in series:
                series[key] = factory()
            return series[key]

    def counter(self, name: str, **labels: str) -> _Counter:
        return self._get(name, "counter", _Counter, labels)

    def gauge(self, name: str, **labels: str) -> _Gauge:
        return self._get(name, "gauge", _Gauge, labels)

    def histogram(self, name: str, buckets: Optional[Sequence[float]] = None,
                  **labels: str) -> _Histogram:
        """``buckets`` fixes the name's ladder at first registration; a later
        call may omit them (reuses the registered ladder) but re-registering
        with a DIFFERENT ladder raises — same-name/different-shape series
        would silently share the first ladder and render corrupt buckets."""
        with self._lock:
            if name in self._types and self._types[name] != "histogram":
                raise ValueError(f"metric {name} already registered as {self._types[name]}")
            requested = tuple(sorted(float(b) for b in buckets)) if buckets else None
            registered = self._hist_buckets.get(name)
            if registered is not None and requested is not None and requested != registered:
                raise ValueError(
                    f"histogram {name} already registered with buckets {registered}; "
                    f"cannot re-register with {requested}"
                )
            effective = registered or requested or _Histogram.BUCKETS
            self._hist_buckets[name] = effective
            self._types[name] = "histogram"
            series = self._metrics.setdefault(name, {})
            key = _label_key(labels)
            if key not in series:
                series[key] = _Histogram(effective)
            return series[key]

    @contextmanager
    def timer(self, name: str, **labels: str):
        """Observe the wall time of a ``with`` body into histogram ``name``
        (the Prometheus *_seconds convention — StepClock and the serving
        paths time phases through this)."""
        hist = self.histogram(name, **labels)
        start = time.perf_counter()
        try:
            yield hist
        finally:
            hist.observe(time.perf_counter() - start)

    def total(self, name: str) -> float:
        """Sum of a counter/gauge across every label combination."""
        with self._lock:
            return sum(
                getattr(m, "value", 0.0) for m in self._metrics.get(name, {}).values()
            )

    def value(self, name: str, **labels: str) -> float:
        with self._lock:
            series = self._metrics.get(name, {})
            m = series.get(_label_key(labels))
            return getattr(m, "value", 0.0) if m else 0.0

    def histogram_counts(
        self, name: str
    ) -> Optional[Tuple[Tuple[float, ...], List[int], int]]:
        """Aggregated ``(buckets, counts, total)`` snapshot of histogram
        ``name`` across every label series. Histograms are cumulative over
        the process lifetime, so consumers that need an *interval* view
        (the SLO autoscaler's windowed p99) snapshot this each tick and
        quantile the per-tick count deltas via ``quantile_from_counts``.
        Returns None when the name has no histogram series."""
        with self._lock:
            series = self._metrics.get(name, {})
            hists = [m for m in series.values() if isinstance(m, _Histogram)]
            if not hists:
                return None
            buckets = hists[0].buckets
            counts = [0] * (len(buckets) + 1)
            total = 0
            for h in hists:
                for i, c in enumerate(h.counts):
                    counts[i] += c
                total += h.total
            return buckets, counts, total

    def quantile(self, name: str, q: float) -> Optional[float]:
        """Estimate the q-quantile (0..1) of histogram ``name`` across every
        label series: find the bucket holding rank q*total and interpolate
        linearly inside it (exactly what PromQL's histogram_quantile does
        server-side). Observations above the largest finite bucket clamp to
        that bound. Returns None for a missing or never-observed histogram —
        "no data" must stay distinguishable from "zero latency" or the SLO
        burn-rate rules would read an outage as a perfect quantile."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile q={q} outside [0, 1]")
        snap = self.histogram_counts(name)
        if snap is None:
            return None
        buckets, counts, total = snap
        return quantile_from_counts(buckets, counts, total, q)

    # -- collectors ----------------------------------------------------------
    def register_collector(self, key: str, fn: Callable[[], None]) -> None:
        """Run ``fn`` at every render() before the exposition is built (the
        Go client's Collector pattern). Keyed: re-registering a key replaces
        it, so mounts stay idempotent. Collectors survive reset()."""
        with self._lock:
            self._collectors[key] = fn

    def render(self) -> str:
        """OpenMetrics-flavored text exposition: Prometheus 0.0.4 sample
        lines, OpenMetrics exemplars on histogram buckets when a trace was
        active, and a terminating ``# EOF`` so the monitoring plane's strict
        parser (kubeflow_tpu/monitoring/scrape.py) round-trips it."""
        for fn in list(self._collectors.values()):
            try:
                fn()  # outside self._lock — collectors call gauge()/counter()
            except Exception:
                pass  # a broken collector must not take /metrics down
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                kind = self._types[name]
                lines.append(f"# TYPE {name} {kind}")
                for key, m in sorted(self._metrics[name].items()):
                    label_str = ",".join(f'{k}="{v}"' for k, v in key)
                    suffix = f"{{{label_str}}}" if label_str else ""
                    if isinstance(m, _Histogram):
                        cum = 0
                        for i, b in enumerate(m.buckets):
                            cum += m.counts[i]
                            le = ("," if label_str else "") + f'le="{b}"'
                            lines.append(
                                f"{name}_bucket{{{label_str}{le}}} {cum}"
                                + _exemplar_suffix(m.exemplars[i])
                            )
                        le = ("," if label_str else "") + 'le="+Inf"'
                        lines.append(
                            f"{name}_bucket{{{label_str}{le}}} {m.total}"
                            + _exemplar_suffix(m.exemplars[-1])
                        )
                        lines.append(f"{name}_sum{suffix} {m.sum}")
                        lines.append(f"{name}_count{suffix} {m.total}")
                    else:
                        lines.append(f"{name}{suffix} {m.value}")
        lines.append("# EOF")  # OpenMetrics terminator: consumers can tell
        return "\n".join(lines) + "\n"  # a complete scrape from a truncated one

    def namespace(self, prefix: str) -> NamespacedRegistry:
        return NamespacedRegistry(self, prefix)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._types.clear()
            self._hist_buckets.clear()


def quantile_from_counts(buckets: Sequence[float], counts: Sequence[int],
                         total: int, q: float) -> Optional[float]:
    """The histogram_quantile() interpolation over an explicit bucket-count
    vector (len(counts) == len(buckets)+1, last slot = +Inf). Shared by the
    registry's cumulative ``quantile`` and windowed consumers quantiling
    per-interval count deltas. Returns None on an empty vector (agreeing
    with ``MetricsRegistry.quantile``): no observations is "no data", never
    a 0.0 that could masquerade as a great latency."""
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    for i, bound in enumerate(buckets):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            lo = buckets[i - 1] if i > 0 else 0.0
            if counts[i] == 0:
                return bound
            return lo + (bound - lo) * ((rank - prev) / counts[i])
    return buckets[-1]  # rank fell in the +Inf bucket: clamp


def _exemplar_suffix(ex: Optional[Tuple[float, str, float]]) -> str:
    if ex is None:
        return ""
    value, trace_id, ts = ex
    return f' # {{trace_id="{trace_id}"}} {value} {round(ts, 3)}'


# -- stdlib process collector -------------------------------------------------

_PROCESS_START = time.time()


def _rss_bytes() -> Optional[float]:
    try:  # Linux: authoritative current RSS
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        pass
    try:  # portable fallback: peak RSS (close enough for a dashboard)
        import resource

        return float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024)
    except Exception:
        return None


def install_process_collector(registry: Optional[MetricsRegistry] = None) -> None:
    """Register the ``process_*`` series (RSS, thread count, GC collections,
    CPU seconds, uptime) on ``registry`` — refreshed at every scrape, the
    promhttp default collector re-built on stdlib."""
    reg = registry if registry is not None else METRICS

    def collect() -> None:
        reg.gauge("process_uptime_seconds").set(time.time() - _PROCESS_START)
        reg.gauge("process_threads").set(float(threading.active_count()))
        t = os.times()
        reg.counter("process_cpu_seconds_total").value = float(t.user + t.system)
        rss = _rss_bytes()
        if rss is not None:
            reg.gauge("process_resident_memory_bytes").set(rss)
        for gen, stats in enumerate(gc.get_stats()):
            reg.counter(
                "process_gc_collections_total", generation=str(gen)
            ).value = float(stats.get("collections", 0))

    reg.register_collector("process", collect)


METRICS = MetricsRegistry()
