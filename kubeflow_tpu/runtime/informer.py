"""Shared informers: watch-backed cached listers for reconcile hot paths.

The round-1 controllers re-listed whole collections on every reconcile —
``_mirror_child_events`` pulled every Event in the namespace,
``_update_running_gauge`` every StatefulSet, the dashboard's
``TpuMetricsService`` every pod in the cluster per request. Each call is
O(collection) across the apiserver boundary, so a 1k-object cluster turns
each reconcile into a full-table scan. The reference never does this: its
client-go controllers and KFAM read through shared informers (the 60-min
informer at access-management/kfam/api_default.go:71-75).

``SharedInformer`` maintains a local mirror of one (apiVersion, kind) fed by
a single watch stream (``send_initial=True`` doubles as the initial list),
reconnecting with a full relist after stream loss — reads are in-memory
dict scans, O(collection) *locally* with zero apiserver round-trips.
``InformerCache`` lazily builds one informer per kind and exposes
client-shaped ``list``/``get``.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Expired
from .metrics import METRICS
from .tracing import TRACER

log = logging.getLogger("kubeflow_tpu.informer")


def _matches(labels: Dict[str, str], selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class SharedInformer:
    """One watch stream → one in-memory mirror of a collection.

    Thread-safe; many consumers share one informer (hence "shared"). Event
    handlers (``on_event(type, obj)``) fire on the watch thread after the
    cache is updated.
    """

    def __init__(self, client: Client, api_version: str, kind: str):
        self.client = client
        self.api_version = api_version
        self.kind = kind
        self._items: Dict[Tuple[Optional[str], str], Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._synced = threading.Event()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._handlers: List[Callable[[str, Dict[str, Any]], None]] = []
        # Secondary indexes (client-go Indexer shape): scanning the whole
        # mirror per reconcile is still O(collection) — at 500 CRs × 30
        # reconciles each that term dominates. name -> key_fn(obj) -> [keys].
        self._index_fns: Dict[str, Callable[[Dict[str, Any]], List[str]]] = {}
        self._indexes: Dict[str, Dict[str, Dict[Tuple[Optional[str], str], Dict[str, Any]]]] = {}
        self._item_keys: Dict[Tuple[Optional[str], str], Dict[str, List[str]]] = {}
        # Highest store resourceVersion this mirror reflects: bumped by every
        # event's object RV and jumped to the snapshot RV at each SYNC
        # marker. wait_rv() is the read-your-writes barrier built on it.
        self._rv_cond = threading.Condition()
        self._last_rv = 0
        self._last_sync_mono: Optional[float] = None
        self._warned_malformed_rv = False

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "SharedInformer":
        if self._thread is not None:
            return self
        # Staleness is the informer failure mode operators actually hit — a
        # wedged watch serves reads forever without erroring. Scrape-time
        # collector so the age keeps growing between syncs; keyed per kind
        # so a replacement informer takes over the series.
        METRICS.register_collector(f"informer_{self.kind}", self._collect)
        self._thread = threading.Thread(
            target=self._pump, name=f"informer-{self.kind}", daemon=True
        )
        self._thread.start()
        return self

    def _collect(self) -> None:
        last = self._last_sync_mono
        if last is not None:
            METRICS.gauge("informer_last_sync_age_seconds", kind=self.kind).set(
                time.monotonic() - last
            )

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            watcher = getattr(self, "_watcher", None)
        if watcher is not None:
            try:
                watcher.close()
            except Exception:
                pass
        # Join the pump: an unjoined daemon thread inside a native-store
        # ctypes call at interpreter exit aborts the process (glibc
        # "exception not rethrown").
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=2.0)

    def wait_synced(self, timeout: float = 10.0) -> bool:
        return self._synced.wait(timeout)

    def wait_rv(self, rv: int, timeout: float = 10.0) -> bool:
        """Block until the mirror reflects store resourceVersion >= rv — a
        read-your-writes barrier (K8s resourceVersionMatch=NotOlderThan).
        Only meaningful for an rv produced by a write to THIS kind (or any
        rv ≤ a sync snapshot): the informer never observes other kinds'
        RVs, so a foreign rv may only resolve at the next reconnect."""
        with self._rv_cond:
            return self._rv_cond.wait_for(lambda: self._last_rv >= rv, timeout)

    def _note_rv(self, rv_str: Any) -> None:
        try:
            rv = int(rv_str)
        except (TypeError, ValueError):
            # A malformed RV quietly disables the wait_rv() barrier for this
            # write — readers fall back to sync timeouts. Count every one,
            # log once per informer so a misbehaving backend is visible
            # without flooding.
            METRICS.counter("informer_malformed_rv_total", kind=self.kind).inc()
            if not self._warned_malformed_rv:
                self._warned_malformed_rv = True
                log.warning(
                    "informer %s: malformed resourceVersion %r; "
                    "read-your-writes barrier degraded for such events",
                    self.kind,
                    rv_str,
                )
            return
        with self._rv_cond:
            if rv > self._last_rv:
                self._last_rv = rv
                self._rv_cond.notify_all()

    @property
    def has_synced(self) -> bool:
        return self._synced.is_set()

    def add_event_handler(self, fn: Callable[[str, Dict[str, Any]], None]) -> None:
        self._handlers.append(fn)

    # -- secondary indexes ----------------------------------------------------
    def add_index(self, name: str, key_fn: Callable[[Dict[str, Any]], List[str]]) -> None:
        """Register (idempotently) an index; existing items are back-filled."""
        with self._lock:
            if name in self._index_fns:
                return
            self._index_fns[name] = key_fn
            self._indexes[name] = {}
            for item_key, obj in self._items.items():
                self._index_add(name, item_key, obj)

    def by_index(self, name: str, key: str) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._indexes.get(name, {}).get(key, {}).values())

    def _index_add(self, name: str, item_key, obj: Dict[str, Any]) -> None:
        try:
            keys = list(self._index_fns[name](obj) or [])
        except Exception:
            log.exception("informer %s: index %s key_fn failed", self.kind, name)
            keys = []
        for k in keys:
            self._indexes[name].setdefault(k, {})[item_key] = obj
        self._item_keys.setdefault(item_key, {})[name] = keys

    def _index_remove(self, item_key) -> None:
        for name, keys in self._item_keys.pop(item_key, {}).items():
            for k in keys:
                bucket = self._indexes[name].get(k)
                if bucket is not None:
                    bucket.pop(item_key, None)
                    if not bucket:
                        del self._indexes[name][k]

    def _apply(self, event_type: str, item_key, obj: Dict[str, Any]) -> None:
        """Cache + index update; caller holds the lock."""
        self._index_remove(item_key)
        if event_type == "DELETED":
            self._items.pop(item_key, None)
        else:
            self._items[item_key] = obj
            for name in self._index_fns:
                self._index_add(name, item_key, obj)

    # -- reads (in-memory, no apiserver round-trip) ---------------------------
    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                obj
                for (ns, _name), obj in self._items.items()
                if (namespace is None or ns == namespace)
                and _matches(apimeta.labels_of(obj), label_selector)
            ]

    def get(self, name: str, namespace: Optional[str] = None) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._items.get((namespace, name))

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    # -- the pump ------------------------------------------------------------
    def _relist(self) -> None:
        """Recover from a compacted watch window (410 Gone): rebuild the
        mirror through the PAGINATED list path — a storm of relisting
        informers must not each issue one giant unbounded LIST — firing
        synthetic DELETED for vanished keys and ADDED/MODIFIED for the rest,
        then resume watching from the snapshot RV."""
        items, rv = self.client.list_paged(self.api_version, self.kind)
        with self._lock:
            fresh = {
                (apimeta.namespace_of(o), apimeta.name_of(o)): o for o in items
            }
            vanished = [
                (k, self._items[k]) for k in list(self._items) if k not in fresh
            ]
            for key, old in vanished:
                self._apply("DELETED", key, old)
            arrived = []
            for key, obj in fresh.items():
                arrived.append(("MODIFIED" if key in self._items else "ADDED", obj))
                self._apply("MODIFIED", key, obj)
        self._note_rv(rv)
        self._last_sync_mono = time.monotonic()
        self._synced.set()
        for _key, old in vanished:
            METRICS.counter("informer_events_total", kind=self.kind, type="DELETED").inc()
            self._dispatch("DELETED", old)
        for type_, obj in arrived:
            METRICS.counter("informer_events_total", kind=self.kind, type=type_).inc()
            self._dispatch(type_, obj)

    def _pump(self) -> None:
        while not self._stopped.is_set():
            # Resume from the last seen RV when we have one: reconnects replay
            # only the missed window (watch cache / journal) instead of
            # re-listing the world. A compacted window (Expired, 410) falls
            # back to the paginated relist. Read under _rv_cond — _note_rv
            # publishes under it, and a stale resume point replays (or with
            # a torn read, skips) part of the window.
            with self._rv_cond:
                resume_rv = self._last_rv
            # a never-synced mirror may be mid-initial-list: resume could
            # permanently miss the unapplied remainder — relist instead
            initial = resume_rv <= 0 or not self._synced.is_set()
            try:
                if initial:
                    watcher = self.client.watch(
                        self.api_version, self.kind, send_initial=True, sync_marker=True
                    )
                else:
                    watcher = self.client.watch(
                        self.api_version, self.kind, since_rv=resume_rv, sync_marker=True
                    )
            except Expired as e:
                log.warning("informer %s: watch window expired (%s); relisting", self.kind, e)
                METRICS.counter("informer_relists_total", kind=self.kind).inc()
                try:
                    # Detached: a relist re-syncs the world for every
                    # consumer; its paginated LISTs must not inherit (and
                    # bill their latency to) whatever request's trace
                    # happens to be current on this thread.
                    with TRACER.detached():
                        self._relist()
                except Exception as e2:
                    log.warning("informer %s: relist failed: %s", self.kind, e2)
                    METRICS.counter("informer_watch_reconnects_total", kind=self.kind).inc()
                    self._stopped.wait(1.0)
                continue
            except Exception as e:
                log.warning("informer %s: watch connect failed: %s", self.kind, e)
                METRICS.counter("informer_watch_reconnects_total", kind=self.kind).inc()
                self._stopped.wait(1.0)
                continue
            with self._lock:
                self._watcher = watcher
            # Relist semantics: the initial ADDED burst overlays the old
            # mirror (no empty-cache window); at the SYNC boundary, every
            # cached key NOT re-sent vanished while we were disconnected —
            # fire synthetic DELETED so handler-maintained state (gauge
            # indexes etc.) can't go stale. client-go emits deletes on
            # relist for exactly this reason. Vanished-key detection is only
            # sound when the stream carried a FULL initial list; an RV-resume
            # stream replays deltas, where absence means "unchanged".
            seen: set = set()
            syncing = True
            try:
                for event in watcher:
                    if event.type == "SYNC":
                        syncing = False
                        vanished = []
                        if initial:
                            with self._lock:
                                vanished = [
                                    (k, self._items[k]) for k in list(self._items) if k not in seen
                                ]
                                for key, old in vanished:
                                    self._apply("DELETED", key, old)
                        self._note_rv((event.object or {}).get("resourceVersion"))
                        self._last_sync_mono = time.monotonic()
                        self._synced.set()
                        for _key, old in vanished:
                            self._dispatch("DELETED", old)
                        continue
                    obj = event.object
                    METRICS.counter(
                        "informer_events_total", kind=self.kind, type=event.type
                    ).inc()
                    key = (apimeta.namespace_of(obj), apimeta.name_of(obj))
                    if syncing:
                        seen.add(key)
                    with self._lock:
                        self._apply(event.type, key, obj)
                    self._note_rv(obj.get("metadata", {}).get("resourceVersion"))
                    self._dispatch(event.type, obj)
            except Exception as e:
                log.warning("informer %s: watch stream error: %s", self.kind, e)
            if not self._stopped.is_set():
                METRICS.counter("informer_watch_reconnects_total", kind=self.kind).inc()
                self._stopped.wait(0.2)

    def _dispatch(self, event_type: str, obj: Dict[str, Any]) -> None:
        for fn in self._handlers:
            try:
                fn(event_type, obj)
            except Exception:
                METRICS.counter("informer_handler_failures_total", kind=self.kind).inc()
                log.exception("informer %s: handler failed", self.kind)


class InformerCache:
    """Lazily-started shared informers keyed by (apiVersion, kind) —
    the read side of a controller-runtime manager's cache."""

    def __init__(self, client: Client):
        self.client = client
        self._informers: Dict[Tuple[str, str], SharedInformer] = {}
        self._lock = threading.Lock()

    def informer_for(self, api_version: str, kind: str) -> SharedInformer:
        key = (api_version, kind)
        with self._lock:
            inf = self._informers.get(key)
            if inf is None:
                inf = SharedInformer(self.client, api_version, kind)
                self._informers[key] = inf
                inf.start()
        return inf

    def list(
        self,
        api_version: str,
        kind: str,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
        sync_timeout: float = 10.0,
        min_rv: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """``min_rv`` is a read-your-writes barrier: wait until the mirror
        reflects that store RV (pass the RV returned by your own write to
        the same kind). On barrier/sync timeout, degrade to a direct list —
        a live read is always fresh enough."""
        inf = self.informer_for(api_version, kind)
        if not inf.wait_synced(sync_timeout) or (
            min_rv is not None and not inf.wait_rv(min_rv, sync_timeout)
        ):
            # Degrade to a direct list rather than serving a stale/empty cache.
            log.warning("informer %s/%s: sync/rv timeout; direct list", api_version, kind)
            return self.client.list(api_version, kind, namespace, label_selector=label_selector)
        return inf.list(namespace, label_selector)

    def get(
        self, api_version: str, kind: str, name: str, namespace: Optional[str] = None,
        sync_timeout: float = 10.0,
    ) -> Optional[Dict[str, Any]]:
        inf = self.informer_for(api_version, kind)
        if not inf.wait_synced(sync_timeout):
            return self.client.get_opt(api_version, kind, name, namespace)
        return inf.get(name, namespace)

    def stop(self) -> None:
        with self._lock:
            for inf in self._informers.values():
                inf.stop()
            self._informers.clear()
