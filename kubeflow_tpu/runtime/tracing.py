"""Tracing: spans over reconcile loops and HTTP handlers.

The reference has NO tracing (SURVEY.md §5 — the closest thing is the
culler's HTTP probe); this is green-field for the TPU build. Design goals:

- OpenTelemetry wire vocabulary (traceId/spanId/parentSpanId, nanosecond
  epochs, status, attributes) so exported JSON loads into any OTLP-adjacent
  tooling without translation,
- zero hard dependency: stdlib only, in-memory ring buffer by default, an
  optional JSON-lines file exporter (KUBEFLOW_TPU_TRACE_FILE),
- near-zero overhead when idle: span creation is a couple of dict ops; no
  locks on the hot path beyond the ring append,
- context propagation: thread-local current span, so nested spans parent
  automatically (reconcile → store call → notify), and an explicit
  ``traceparent`` header codec for cross-service HTTP hops (the
  dashboard BFF → KFAM call chain).
"""

from __future__ import annotations

import collections
import json
import os
import socket
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterator, List, Optional

_local = threading.local()

#: annotation carrying the creating request's W3C ``traceparent`` — stamped
#: by the apiserver on create, so watch-driven reconciles (and the gang
#: lifecycle trace) parent to the client call that caused the object
TRACEPARENT_ANNOTATION = "tracing.kubeflow.org/traceparent"

#: annotation the scheduler stamps on the bind write (same update that sets
#: ``spec.nodeName``): the gang trace's bind span, so podlet/engine/training
#: spans started off the bound pod join the same trace
BIND_TRACEPARENT_ANNOTATION = "tracing.kubeflow.org/bind-traceparent"

#: default TTL for ``start_span()`` spans never ended (a crashed worker):
#: past it the sweep force-closes them as ERROR and counts
#: ``tracing_spans_abandoned_total``
OPEN_SPAN_TTL_S = 600.0


def _rand_hex(nbytes: int) -> str:
    # os.urandom, NOT the random module: seeded tests (random.seed(0) in a
    # fixture) and forked workers would otherwise mint colliding ids.
    return os.urandom(nbytes).hex()


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    status: str = "OK"  # OK | ERROR
    status_message: str = ""

    def set(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attrs: Any) -> "Span":
        self.events.append({"name": name, "timeUnixNano": time.time_ns(), "attributes": attrs})
        return self

    def record_error(self, exc: BaseException) -> "Span":
        self.status = "ERROR"
        self.status_message = f"{type(exc).__name__}: {exc}"
        return self

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "startTimeUnixNano": self.start_ns,
            "endTimeUnixNano": self.end_ns,
            "status": {"code": self.status, "message": self.status_message},
            "attributes": self.attributes,
        }
        if self.parent_span_id:
            d["parentSpanId"] = self.parent_span_id
        if self.events:
            d["events"] = self.events
        return d


class Tracer:
    """Span factory + ring-buffer store (+ optional JSON-lines export)."""

    def __init__(self, service: str = "kubeflow-tpu", capacity: int = 4096,
                 export_path: Optional[str] = None,
                 instance: Optional[str] = None,
                 open_span_ttl_s: float = OPEN_SPAN_TTL_S):
        self.service = service
        #: OTLP resource identity (service.instance.id): which process a
        #: federated span came from — the TraceCollector's assembly key
        self.instance = instance or f"{socket.gethostname()}:{os.getpid()}"
        self._spans: Deque[Span] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        # cross-thread open-span map: every start_span() registers here and
        # end_span() removes; bounded by the TTL sweep plus a hard cap so a
        # caller that never calls end_span can't grow memory forever
        self.open_span_ttl_s = open_span_ttl_s
        self.max_open_spans = capacity
        self._open: Dict[str, Span] = {}
        self._open_lock = threading.Lock()
        self._last_sweep = time.monotonic()
        self._export_path = export_path or os.environ.get("KUBEFLOW_TPU_TRACE_FILE")
        self._export_file = None  # opened lazily, kept for the tracer's life
        # export serializes on its OWN lock: a slow disk must stall at most
        # the exporting threads, never every traced thread (the ring lock
        # is held only for the O(1) append)
        self._export_lock = threading.Lock()

    # -- context -------------------------------------------------------------
    @staticmethod
    def current_span() -> Optional[Span]:
        return getattr(_local, "span", None)

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        traceparent: Optional[str] = None,
        **attributes: Any,
    ) -> Span:
        """Manual span lifecycle: parents to (in order) the explicit parent,
        a ``traceparent`` header, or the thread-local current span, but does
        NOT become the current span — the shape for work that starts on one
        thread and finishes on another (a serving request lives from the
        HTTP handler thread's submit() to the engine worker's retire()).
        Pair with ``end_span()`` to record it."""
        if parent is None and traceparent:
            parsed = parse_traceparent(traceparent)
            if parsed:
                trace_id, parent_span_id = parsed
                parent = Span("remote", trace_id, parent_span_id)
        if parent is None:
            parent = self.current_span()
        span = Span(
            name=name,
            trace_id=parent.trace_id if parent else _rand_hex(16),
            span_id=_rand_hex(8),
            parent_span_id=parent.span_id if parent else None,
            start_ns=time.time_ns(),
            attributes={"service.name": self.service, **attributes},
        )
        with self._open_lock:
            self._open[span.span_id] = span
            over = len(self._open) - self.max_open_spans
            oldest = (sorted(self._open.values(), key=lambda s: s.start_ns)[:over]
                      if over > 0 else [])
            for stale in oldest:
                del self._open[stale.span_id]
        if oldest:
            self._abandon(oldest, f"evicted: >{self.max_open_spans} open spans")
        self._maybe_sweep()
        return span

    def end_span(self, span: Span, error: Optional[BaseException] = None) -> Span:
        """Close and record a ``start_span()`` span (idempotence is the
        caller's business)."""
        with self._open_lock:
            self._open.pop(span.span_id, None)
        if error is not None:
            span.record_error(error)
        span.end_ns = time.time_ns()
        self._record(span)
        return span

    def open_spans(self) -> List[Span]:
        """Spans started but not yet ended (debug/test view)."""
        with self._open_lock:
            return list(self._open.values())

    def _maybe_sweep(self) -> None:
        # amortized: at most one sweep per quarter-TTL, checked with one
        # monotonic read on the start_span hot path
        if time.monotonic() - self._last_sweep < self.open_span_ttl_s / 4:
            return
        self.sweep_abandoned()

    def sweep_abandoned(self, ttl_s: Optional[float] = None) -> int:
        """Force-close open spans older than the TTL (their worker crashed or
        forgot end_span): recorded as ERROR and counted by
        ``tracing_spans_abandoned_total`` so the leak is visible, while the
        open-span map stays bounded."""
        ttl = self.open_span_ttl_s if ttl_s is None else ttl_s
        self._last_sweep = time.monotonic()
        cutoff = time.time_ns() - int(ttl * 1e9)
        with self._open_lock:
            stale = [s for s in self._open.values() if s.start_ns <= cutoff]
            for s in stale:
                del self._open[s.span_id]
        self._abandon(stale, f"abandoned: not ended within {ttl:.0f}s")
        return len(stale)

    def _abandon(self, spans: List[Span], message: str) -> None:
        if not spans:
            return
        # metrics is imported lazily: no import-time cycle (metrics reaches
        # back into this module for exemplar trace ids the same way)
        from .metrics import METRICS

        for s in spans:
            s.status = "ERROR"
            s.status_message = message
            s.end_ns = time.time_ns()
            self._record(s)
            METRICS.counter("tracing_spans_abandoned_total").inc()

    def emit_span(
        self,
        name: str,
        start_ns: int,
        end_ns: int,
        events: Optional[List[Dict[str, Any]]] = None,
        parent: Optional[Span] = None,
        **attributes: Any,
    ) -> Span:
        """Record an already-elapsed interval as a span (StepClock's per-step
        hook: the step is only known to be a span at ``end_step()``)."""
        if parent is None:
            parent = self.current_span()
        span = Span(
            name=name,
            trace_id=parent.trace_id if parent else _rand_hex(16),
            span_id=_rand_hex(8),
            parent_span_id=parent.span_id if parent else None,
            start_ns=start_ns,
            end_ns=end_ns,
            attributes={"service.name": self.service, **attributes},
        )
        if events:
            span.events = list(events)
        self._record(span)
        return span

    @contextmanager
    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        traceparent: Optional[str] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        """Open a span; parents to (in order) the explicit parent, a
        ``traceparent`` header, or the thread-local current span."""
        span = self.start_span(name, parent=parent, traceparent=traceparent,
                               **attributes)
        prev = self.current_span()
        _local.span = span
        try:
            yield span
        except BaseException as e:
            span.record_error(e)
            raise
        finally:
            _local.span = prev
            self.end_span(span)

    @contextmanager
    def detached(self) -> Iterator[None]:
        """Run with NO current span: for work triggered from inside a
        request's context that is not part of that request (an informer
        410-relist re-syncs the world for everyone — its outbound LISTs
        must not inherit the triggering stream's trace)."""
        prev = self.current_span()
        _local.span = None
        try:
            yield
        finally:
            _local.span = prev

    # -- storage / export ----------------------------------------------------
    def _record(self, span: Span) -> None:
        with self._lock:
            self._spans.append(span)
        if self._export_path:
            # serialize + write OUTSIDE the ring lock: readers and other
            # recording threads must never wait on a slow disk
            line = json.dumps(span.to_dict()) + "\n"
            with self._export_lock:
                try:
                    if self._export_file is None:
                        self._export_file = open(self._export_path, "a")
                    self._export_file.write(line)
                    self._export_file.flush()
                except OSError:
                    pass  # tracing must never take the control plane down

    def finished_spans(self, name: Optional[str] = None, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        return spans

    def trace_tree(self, trace_id: str) -> Dict[str, List[Span]]:
        """children-by-parent index of one trace (test/debug helper)."""
        tree: Dict[str, List[Span]] = {}
        for s in self.finished_spans(trace_id=trace_id):
            tree.setdefault(s.parent_span_id or "", []).append(s)
        return tree

    def to_chrome_trace(self, name: Optional[str] = None,
                        trace_id: Optional[str] = None,
                        limit: int = 4096) -> Dict[str, Any]:
        """The ring buffer's tail as a Chrome-trace-event document (the
        ``trace.json`` format Perfetto / chrome://tracing load directly) —
        the offline-visualization counterpart to the OTLP-shaped
        ``/debug/traces``. Span events become instant events on the same
        track, so a ``train.step`` span shows its phase marks inline."""
        spans = self.finished_spans(name=name, trace_id=trace_id)[-max(0, limit):]
        return {"traceEvents": spans_to_chrome_trace(spans),
                "displayTimeUnit": "ms"}

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()
        with self._open_lock:
            self._open.clear()


# -- Chrome trace events (the Perfetto-loadable export) -----------------------

def spans_to_chrome_trace(spans: List[Span]) -> List[Dict[str, Any]]:
    """Spans → Chrome trace events: one complete ("ph": "X") event per span
    (ts/dur in microseconds, as the format requires) plus one instant
    ("ph": "i") event per span event. Spans of one trace share a ``tid`` so
    a request's hops stack on one track; ``pid`` is the real process."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = []
    tids: Dict[str, int] = {}
    for span in spans:
        tid = tids.setdefault(span.trace_id, len(tids) + 1)
        events.append({
            "name": span.name,
            "cat": str(span.attributes.get("service.name", "span")),
            "ph": "X",
            "ts": span.start_ns / 1e3,
            "dur": max(0.0, (span.end_ns - span.start_ns) / 1e3),
            "pid": pid,
            "tid": tid,
            "args": {k: v for k, v in span.attributes.items()
                     if k != "service.name"},
        })
        for ev in span.events:
            events.append({
                "name": ev.get("name", "event"),
                "cat": "event",
                "ph": "i",
                "s": "t",
                "ts": ev.get("timeUnixNano", span.end_ns) / 1e3,
                "pid": pid,
                "tid": tid,
                "args": dict(ev.get("attributes", {})),
            })
    return events


# -- W3C traceparent codec (the cross-service hop) ---------------------------

def format_traceparent(span: Span) -> str:
    return f"00-{span.trace_id}-{span.span_id}-01"


def parse_traceparent(header: str) -> Optional[tuple]:
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


#: process-global tracer (mirrors METRICS's process-global registry)
TRACER = Tracer()
