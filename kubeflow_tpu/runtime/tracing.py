"""Tracing: spans over reconcile loops and HTTP handlers.

The reference has NO tracing (SURVEY.md §5 — the closest thing is the
culler's HTTP probe); this is green-field for the TPU build. Design goals:

- OpenTelemetry wire vocabulary (traceId/spanId/parentSpanId, nanosecond
  epochs, status, attributes) so exported JSON loads into any OTLP-adjacent
  tooling without translation,
- zero hard dependency: stdlib only, in-memory ring buffer by default, an
  optional JSON-lines file exporter (KUBEFLOW_TPU_TRACE_FILE),
- near-zero overhead when idle: span creation is a couple of dict ops; no
  locks on the hot path beyond the ring append,
- context propagation: thread-local current span, so nested spans parent
  automatically (reconcile → store call → notify), and an explicit
  ``traceparent`` header codec for cross-service HTTP hops (the
  dashboard BFF → KFAM call chain).
"""

from __future__ import annotations

import collections
import json
import os
import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterator, List, Optional

_local = threading.local()


def _rand_hex(nbytes: int) -> str:
    return "".join(f"{random.getrandbits(8):02x}" for _ in range(nbytes))


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None
    start_ns: int = 0
    end_ns: int = 0
    attributes: Dict[str, Any] = field(default_factory=dict)
    events: List[Dict[str, Any]] = field(default_factory=list)
    status: str = "OK"  # OK | ERROR
    status_message: str = ""

    def set(self, key: str, value: Any) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attrs: Any) -> "Span":
        self.events.append({"name": name, "timeUnixNano": time.time_ns(), "attributes": attrs})
        return self

    def record_error(self, exc: BaseException) -> "Span":
        self.status = "ERROR"
        self.status_message = f"{type(exc).__name__}: {exc}"
        return self

    @property
    def duration_ms(self) -> float:
        return (self.end_ns - self.start_ns) / 1e6

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "startTimeUnixNano": self.start_ns,
            "endTimeUnixNano": self.end_ns,
            "status": {"code": self.status, "message": self.status_message},
            "attributes": self.attributes,
        }
        if self.parent_span_id:
            d["parentSpanId"] = self.parent_span_id
        if self.events:
            d["events"] = self.events
        return d


class Tracer:
    """Span factory + ring-buffer store (+ optional JSON-lines export)."""

    def __init__(self, service: str = "kubeflow-tpu", capacity: int = 4096,
                 export_path: Optional[str] = None):
        self.service = service
        self._spans: Deque[Span] = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._export_path = export_path or os.environ.get("KUBEFLOW_TPU_TRACE_FILE")
        self._export_file = None  # opened lazily, kept for the tracer's life

    # -- context -------------------------------------------------------------
    @staticmethod
    def current_span() -> Optional[Span]:
        return getattr(_local, "span", None)

    @contextmanager
    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        traceparent: Optional[str] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        """Open a span; parents to (in order) the explicit parent, a
        ``traceparent`` header, or the thread-local current span."""
        if parent is None and traceparent:
            parsed = parse_traceparent(traceparent)
            if parsed:
                trace_id, parent_span_id = parsed
                parent = Span("remote", trace_id, parent_span_id)
        if parent is None:
            parent = self.current_span()
        span = Span(
            name=name,
            trace_id=parent.trace_id if parent else _rand_hex(16),
            span_id=_rand_hex(8),
            parent_span_id=parent.span_id if parent else None,
            start_ns=time.time_ns(),
            attributes={"service.name": self.service, **attributes},
        )
        prev = self.current_span()
        _local.span = span
        try:
            yield span
        except BaseException as e:
            span.record_error(e)
            raise
        finally:
            span.end_ns = time.time_ns()
            _local.span = prev
            self._record(span)

    # -- storage / export ----------------------------------------------------
    def _record(self, span: Span) -> None:
        line = json.dumps(span.to_dict()) + "\n" if self._export_path else None
        with self._lock:
            self._spans.append(span)
            if line is not None:
                try:
                    if self._export_file is None:
                        self._export_file = open(self._export_path, "a")
                    self._export_file.write(line)
                    self._export_file.flush()
                except OSError:
                    pass  # tracing must never take the control plane down

    def finished_spans(self, name: Optional[str] = None, trace_id: Optional[str] = None) -> List[Span]:
        with self._lock:
            spans = list(self._spans)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        return spans

    def trace_tree(self, trace_id: str) -> Dict[str, List[Span]]:
        """children-by-parent index of one trace (test/debug helper)."""
        tree: Dict[str, List[Span]] = {}
        for s in self.finished_spans(trace_id=trace_id):
            tree.setdefault(s.parent_span_id or "", []).append(s)
        return tree

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


# -- W3C traceparent codec (the cross-service hop) ---------------------------

def format_traceparent(span: Span) -> str:
    return f"00-{span.trace_id}-{span.span_id}-01"


def parse_traceparent(header: str) -> Optional[tuple]:
    parts = header.strip().split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


#: process-global tracer (mirrors METRICS's process-global registry)
TRACER = Tracer()
