"""python -m kubeflow_tpu — the all-in-one control plane.

One process hosting the REST apiserver, the full controller set, and every
web service on consecutive ports: the single-binary dev/demo deployment
(the per-role manifests split exactly this composition across Deployments).

Env: API_PORT (8001), DASHBOARD_PORT (8082), JUPYTER_PORT (5001),
TENSORBOARDS_PORT (5002), VOLUMES_PORT (5003), KFAM_PORT (8081),
APP_DISABLE_AUTH for local use; APISERVER_AUTH=token (+ APISERVER_TOKENS /
APISERVER_TOKEN_FILE) turns on the same deny-by-default REST gate as the
per-role apiserver (apiserver/auth.py).
"""

from __future__ import annotations

import logging
import os

from .apiserver.server import make_apiserver_app
from .platform import build_platform
from .runtime.bootstrap import auth_from_env, block_forever
from .services.dashboard import make_dashboard_app
from .services.jupyter import make_jupyter_app
from .services.kfam import make_kfam_app
from .services.tensorboards import make_tensorboards_app
from .services.volumes import make_volumes_app

log = logging.getLogger("kubeflow_tpu")


def main() -> None:
    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    mgr = build_platform().start()
    store, client = mgr.store, mgr.client
    auth = auth_from_env()

    # Manager.start() already runs the GC sweep on this same Store; REST
    # writers are covered by it (no second sweep needed here). The same
    # APISERVER_AUTH=token gate as the per-role server applies (off by
    # default for local/dev use; in-process components bypass REST anyway).
    from .apiserver.auth import auth_from_env as api_auth_from_env

    servers = [("apiserver", make_apiserver_app(
        store, auth=api_auth_from_env(store),
    ).serve(int(os.environ.get("API_PORT", "8001"))))]

    # ONE InformerCache for every co-hosted app: kfam, dashboard, and
    # jupyter all mirror overlapping kinds (Namespace, Node, Event) — a
    # private cache each would mean duplicate watch streams and duplicate
    # O(cluster) mirrors in the same process.
    from .runtime.informer import InformerCache

    shared_cache = InformerCache(client)
    kfam_app = make_kfam_app(client, auth, cache=shared_cache)
    for name, app, port_env, default in [
        ("kfam", kfam_app, "KFAM_PORT", 8081),
        ("dashboard", make_dashboard_app(client, kfam_app, auth, cache=shared_cache), "DASHBOARD_PORT", 8082),
        ("jupyter", make_jupyter_app(client, auth=auth, cache=shared_cache), "JUPYTER_PORT", 5001),
        ("tensorboards", make_tensorboards_app(client, auth), "TENSORBOARDS_PORT", 5002),
        ("volumes", make_volumes_app(client, auth), "VOLUMES_PORT", 5003),
    ]:
        servers.append((name, app.serve(int(os.environ.get(port_env, str(default))))))

    for name, server in servers:
        log.info("%s: http://127.0.0.1:%d", name, server.port)
    try:
        block_forever()
    finally:
        for _, server in servers:
            server.close()
        mgr.stop()


if __name__ == "__main__":
    main()
