"""Input pipeline: host-side prefetch + device double-buffering.

The training-side data path (the reference delegates data entirely to
workload images): keep the TPU fed by overlapping host work (decode,
augment, batch assembly) with device compute, and place each batch onto
the mesh with the right sharding before the step needs it.
"""

from .pipeline import DataPipeline, device_prefetch, per_host_shard, synthetic_classifier_source

__all__ = [
    "DataPipeline",
    "device_prefetch",
    "per_host_shard",
    "synthetic_classifier_source",
]
