"""Prefetching input pipeline for sharded training.

Design (the standard TPU input recipe):

- a background thread pulls batches from the (CPU-bound) source and
  ``jax.device_put``s them with the target sharding — dispatch is async, so
  the H2D copy of batch N+1 overlaps the compute of batch N,
- a small bounded buffer (default 2 = double buffering) keeps host memory
  flat while hiding host latency spikes,
- multi-host: each process feeds only its addressable shard of the global
  batch (``per_host_shard`` → ``jax.make_array_from_process_local_data``),
  the same contract a grain/tf.data per-worker reader satisfies.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional, Tuple

import jax
import numpy as np


def per_host_shard(global_batch: int, *, process_index: Optional[int] = None,
                   process_count: Optional[int] = None) -> Tuple[int, int]:
    """(start, size) of this host's rows in the global batch — which examples
    this process's reader must produce."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    if global_batch % pc != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {pc} hosts")
    size = global_batch // pc
    return pi * size, size


def device_prefetch(
    source: Iterable[Any],
    sharding: Optional[Any] = None,
    buffer_size: int = 2,
    clock: Optional[Any] = None,
) -> Iterator[Any]:
    """Iterate ``source`` with async device placement, ``buffer_size`` deep.

    Each item is a pytree of numpy arrays; it is ``device_put`` (with
    ``sharding`` if given) on a background thread, so the returned device
    buffers are usually already resident when the consumer asks.

    ``clock`` (a ``tpu.profiling.StepClock``) charges the consumer-side
    queue wait to its ``data_wait`` phase — zero when prefetch is keeping
    up, the input-bound signal when it isn't.
    """
    q: "queue.Queue[Any]" = queue.Queue(maxsize=max(1, buffer_size))
    _END = object()
    error: list = []
    stop = threading.Event()

    def _put(item: Any) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def produce() -> None:
        try:
            for item in source:
                if stop.is_set():
                    return
                if sharding is not None:
                    item = jax.device_put(item, sharding)
                else:
                    item = jax.device_put(item)
                if not _put(item):
                    return
        except BaseException as e:  # surfaced on the consumer side
            error.append(e)
        finally:
            _put(_END)

    t = threading.Thread(target=produce, name="data-prefetch", daemon=True)
    t.start()
    try:
        while True:
            if clock is not None:
                with clock.data_wait():
                    item = q.get()
            else:
                item = q.get()
            if item is _END:
                if error:
                    raise error[0]
                return
            yield item
    finally:
        # Abandoned mid-epoch (break / GeneratorExit): release the producer —
        # it must not stay blocked on a full queue pinning device buffers.
        stop.set()
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                break


class DataPipeline:
    """Source → (optional transform) → prefetched, sharded device batches.

    ``source_fn(epoch) -> iterable of batches`` lets epochs reshuffle;
    ``transform`` runs on the host thread (augmentation, casting).
    """

    def __init__(
        self,
        source_fn: Callable[[int], Iterable[Any]],
        sharding: Optional[Any] = None,
        transform: Optional[Callable[[Any], Any]] = None,
        buffer_size: int = 2,
        clock: Optional[Any] = None,
    ):
        self.source_fn = source_fn
        self.sharding = sharding
        self.transform = transform
        self.buffer_size = buffer_size
        self.clock = clock

    def epoch(self, epoch: int = 0) -> Iterator[Any]:
        source: Iterable[Any] = self.source_fn(epoch)
        if self.transform is not None:
            transform = self.transform
            source = (transform(item) for item in source)
        return device_prefetch(source, self.sharding, self.buffer_size,
                               clock=self.clock)

    def __iter__(self) -> Iterator[Any]:
        return self.epoch(0)


def synthetic_classifier_source(
    batch: int,
    image_shape: Tuple[int, ...] = (224, 224, 3),
    num_classes: int = 1000,
    steps: int = 100,
    seed: int = 0,
) -> Callable[[int], Iterable[Any]]:
    """Deterministic synthetic (images, labels) batches — bench/smoke data
    with zero I/O (the compute path isolation bench.py relies on)."""

    def source(epoch: int):
        rng = np.random.default_rng(seed + epoch)
        for _ in range(steps):
            yield {
                "images": rng.standard_normal((batch, *image_shape), dtype=np.float32),
                "labels": rng.integers(0, num_classes, size=(batch,), dtype=np.int32),
            }

    return source
