"""StudyJob controller: Katib-class HPO orchestration on TPU slices.

The reference treats the StudyJob controller as an external system its e2e
merely polls (testing/katib_studyjob_test.py:128-193 waits for
``status.condition == Running``). Here it is a first-class in-tree
controller:

- ``StudyJob`` CR: objective + parameter space + algorithm +
  parallel/max trial counts + a trial template (optionally with a
  ``tpu`` block so every trial lands on its own slice),
- suggestion via kubeflow_tpu.hpo (random/grid/bayesian); the suggester is
  rebuilt deterministically from completed Trial CRs, so controller
  restarts lose nothing (level-triggered, like every reconciler here),
- ``Trial`` CRs own the execution; a trial runner materializes each trial
  (pods in production via TrialPodRunner — same admission/scheduling path
  as notebooks; an in-process executor in CPU CI runs real JAX training),
- status: Created → Running → Completed/Failed, trial counts, and
  ``currentOptimalTrial`` (the reference's Katib surface).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Callable, Dict, List, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..hpo import earlystop
from ..hpo.suggest import GridSuggester, ParamSpec, make_suggester
from ..runtime.manager import Reconciler, Request, Result
from ..runtime.metrics import METRICS
from ..scheduler.gang import POD_GROUP_LABEL, POD_GROUP_SIZE_ANNOTATION

log = logging.getLogger("kubeflow_tpu.studyjob")

STUDY_API = "katib.kubeflow.org/v1alpha1"
TRIAL_LABEL = "studyjob-name"


def param_specs_of(study: Dict[str, Any]) -> List[ParamSpec]:
    specs = []
    for p in study.get("spec", {}).get("parameters", []) or []:
        feasible = p.get("feasibleSpace") or {}
        specs.append(
            ParamSpec(
                name=p["name"],
                type=p.get("parameterType", "double"),
                min=_maybe_float(feasible.get("min")),
                max=_maybe_float(feasible.get("max")),
                values=feasible.get("list") or (),
                log_scale=bool(feasible.get("logScale")),
            )
        )
    if not specs:
        raise ValueError("studyjob has no parameters")
    return specs


def _maybe_float(v: Any) -> Optional[float]:
    return None if v is None else float(v)


class StudyJobReconciler(Reconciler):
    FOR = (STUDY_API, "StudyJob")
    OWNS = [(STUDY_API, "Trial")]

    def reconcile(self, client: Client, req: Request) -> Result:
        study = client.get_opt(*self.FOR, req.name, req.namespace)
        if study is None:
            return Result()
        spec = study.get("spec", {})
        status = dict(study.get("status") or {})
        phase = status.get("phase", "Created")
        if phase in ("Completed", "Failed"):
            return Result()

        try:
            specs = param_specs_of(study)
            objective = spec.get("objective") or {}
            maximize = objective.get("type", "maximize") == "maximize"
            algorithm = (spec.get("algorithm") or {}).get("algorithmName", "random")
            suggester = make_suggester(algorithm, specs, maximize, seed=spec.get("seed", 0))
            early = earlystop.parse_early_stopping(spec)
        except ValueError as e:
            self._set_status(client, study, {"phase": "Failed", "reason": "InvalidSpec", "message": str(e)})
            METRICS.counter("studyjob_failed_total").inc()
            return Result()

        trials = [
            t
            for t in client.list(STUDY_API, "Trial", req.namespace)
            if apimeta.labels_of(t).get(TRIAL_LABEL) == req.name
        ]
        completed = [t for t in trials if t.get("status", {}).get("phase") == "Succeeded"]
        failed = [t for t in trials if t.get("status", {}).get("phase") == "Failed"]
        pruned = [t for t in trials if t.get("status", {}).get("phase") == "Pruned"]
        active = [t for t in trials
                  if t not in completed and t not in failed and t not in pruned]

        metric_name = objective.get("objectiveMetricName", "objective")
        # Pruned trials feed the suggester too: their last observation is a
        # real (censored) measurement — dropping it would make the GP re-ask
        # near known-bad regions.
        for t in completed + pruned:
            value = (t.get("status", {}).get("metrics") or {}).get(metric_name)
            if value is not None:
                suggester.tell(t.get("spec", {}).get("parameters", {}), float(value))

        if early is not None and active:
            self._apply_median_stopping(client, active, completed, maximize, early)

        max_trials = int(spec.get("maxTrialCount", 10))
        parallel = int(spec.get("parallelTrialCount", 3))
        goal = objective.get("goal")

        goal_reached = False
        best = suggester.best()
        if best is not None and goal is not None:
            goal_reached = best.objective >= float(goal) if maximize else best.objective <= float(goal)

        done = len(completed) + len(failed) + len(pruned)
        exhausted = False
        if isinstance(suggester, GridSuggester):
            # Fast-forward the deterministic grid cursor past every point a
            # trial has already been created for. If that reaches the end of
            # the grid, the search space is exhausted: the study completes as
            # soon as the in-flight trials finish, even when the grid is
            # smaller than maxTrialCount (otherwise it would never complete).
            suggester.ask(len(trials))
            exhausted = suggester.exhausted
        if (done >= max_trials or goal_reached or exhausted) and not active:
            new_status = {
                "phase": "Completed",
                "trialsTotal": len(trials),
                "trialsSucceeded": len(completed),
                "trialsFailed": len(failed),
                "trialsPruned": len(pruned),
                "goalReached": goal_reached,
            }
            if exhausted and not goal_reached and done < max_trials:
                new_status["reason"] = "SearchSpaceExhausted"
            if best:
                new_status["currentOptimalTrial"] = {
                    "parameterAssignments": best.params,
                    "observation": {metric_name: best.objective},
                }
            self._set_status(client, study, new_status)
            METRICS.counter("studyjob_completed_total").inc()
            return Result()

        want_new = 0
        if not goal_reached:
            budget_left = max_trials - done - len(active)
            want_new = max(0, min(parallel - len(active), budget_left))
        created = 0
        if want_new:
            # The grid cursor was already fast-forwarded above; an exhausted
            # grid returns fewer (possibly zero) points than asked.
            for params in suggester.ask(want_new):
                self._create_trial(client, study, params, index=len(trials))
                trials.append({})  # count for naming
                created += 1
                METRICS.counter("studyjob_trials_created_total").inc()

        new_status = {
            "phase": "Running",
            "trialsTotal": len(trials),
            "trialsSucceeded": len(completed),
            "trialsFailed": len(failed),
            "trialsPruned": len(pruned),
            "trialsRunning": len(active) + created,
        }
        if best:
            new_status["currentOptimalTrial"] = {
                "parameterAssignments": best.params,
                "observation": {metric_name: best.objective},
            }
        self._set_status(client, study, new_status)
        return Result()

    def _apply_median_stopping(
        self,
        client: Client,
        active: List[Dict[str, Any]],
        completed: List[Dict[str, Any]],
        maximize: bool,
        early: Dict[str, Any],
    ) -> None:
        """Mark active losers with the early-stop annotation (the trial side
        reads it at its next intermediate report and exits — earlystop.py)."""
        histories = {
            apimeta.name_of(t): earlystop.observations_of(t) for t in active + completed
        }
        for t in active:
            name = apimeta.name_of(t)
            if earlystop.EARLY_STOP_ANNOTATION in apimeta.annotations_of(t):
                continue
            mine = histories.get(name) or []
            others = [h for n, h in histories.items() if n != name and h]
            if earlystop.should_stop(
                mine, others, maximize=maximize,
                min_trials=early["min_trials"], min_step=early["min_step"],
            ):
                client.patch(
                    STUDY_API, "Trial", name,
                    {"metadata": {"annotations": {
                        earlystop.EARLY_STOP_ANNOTATION: "medianstop"}}},
                    apimeta.namespace_of(t),
                )
                METRICS.counter("studyjob_trials_pruned_total").inc()

    def _create_trial(
        self, client: Client, study: Dict[str, Any], params: Dict[str, Any], index: int
    ) -> None:
        name = f"{apimeta.name_of(study)}-trial-{index}"
        trial = apimeta.new_object(
            STUDY_API,
            "Trial",
            name,
            apimeta.namespace_of(study),
            labels={TRIAL_LABEL: apimeta.name_of(study)},
            spec={
                "parameters": params,
                "template": apimeta.deepcopy(study.get("spec", {}).get("trialTemplate") or {}),
                "objectiveMetricName": (study.get("spec", {}).get("objective") or {}).get(
                    "objectiveMetricName", "objective"
                ),
            },
        )
        apimeta.set_owner_reference(trial, study)
        client.create(trial)

    def _set_status(self, client: Client, study: Dict[str, Any], status: Dict[str, Any]) -> None:
        fresh = client.get_opt(*self.FOR, apimeta.name_of(study), apimeta.namespace_of(study))
        if fresh is None or fresh.get("status") == status:
            return
        fresh = apimeta.deepcopy(fresh)
        fresh["status"] = status
        client.update_status(fresh)


class TrialPodRunner(Reconciler):
    """Materializes Trial CRs as pods (production path).

    The pod carries the trial parameters as JSON in ``TRIAL_PARAMETERS`` env
    plus per-parameter ``PARAM_<NAME>`` vars, the studyjob labels (so TPU
    PodDefaults match and inject slice env/limits), and the reporter
    contract env (``TRIAL_NAME``/``TRIAL_NAMESPACE``/``TRIAL_OBJECTIVE``/
    ``APISERVER_URL``): the trial entrypoint (images/trial-jax-tpu →
    ``python -m kubeflow_tpu.hpo.reporter``) runs the objective and PATCHes
    ``{metric: value}`` back as the ``results`` annotation, which this
    reconciler folds into trial status. Pod phase carries success/failure.
    """

    FOR = (STUDY_API, "Trial")
    OWNS = [("v1", "Pod")]

    def __init__(self, apiserver_url: Optional[str] = None):
        import os

        from ..runtime.bootstrap import DEFAULT_APISERVER

        self.apiserver_url = apiserver_url or os.environ.get("APISERVER_URL", DEFAULT_APISERVER)

    def reconcile(self, client: Client, req: Request) -> Result:
        trial = client.get_opt(*self.FOR, req.name, req.namespace)
        if trial is None:
            return Result()
        phase = trial.get("status", {}).get("phase")
        if phase in ("Succeeded", "Failed"):
            return Result()

        pod = client.get_opt("v1", "Pod", req.name, req.namespace)
        if pod is None:
            template = trial.get("spec", {}).get("template") or {}
            params = trial.get("spec", {}).get("parameters", {})
            container = {
                "name": "trial",
                "image": template.get("image", "kubeflow-tpu/trial-jax:latest"),
                "command": template.get("command") or [],
                "env": [
                    {"name": "TRIAL_PARAMETERS", "value": json.dumps(params, sort_keys=True)},
                    {"name": "TRIAL_NAME", "value": req.name},
                    {"name": "TRIAL_NAMESPACE", "value": req.namespace or ""},
                    {"name": "TRIAL_OBJECTIVE", "value": template.get("objective", "mnist")},
                    {"name": "APISERVER_URL", "value": self.apiserver_url},
                ]
                + [
                    {"name": f"PARAM_{k.upper()}", "value": str(v)}
                    for k, v in sorted(params.items())
                ],
            }
            pod = apimeta.new_object(
                "v1",
                "Pod",
                req.name,
                req.namespace,
                labels={
                    **apimeta.labels_of(trial),
                    "trial-name": req.name,
                    # each trial is its own gang: preemptable as a unit, and
                    # a notebook-class gang may evict it for chips
                    POD_GROUP_LABEL: req.name,
                },
                annotations={POD_GROUP_SIZE_ANNOTATION: "1"},
                spec={
                    "containers": [container],
                    "restartPolicy": "Never",
                    "priorityClassName": "trial",
                },
            )
            apimeta.set_owner_reference(pod, trial)
            client.create(pod)
            self._set_phase(client, trial, "Running")
            return Result()

        annotations = apimeta.annotations_of(trial)
        pod_phase = pod.get("status", {}).get("phase")
        results = annotations.get("results")
        observations = self._parse_observations(annotations)
        if pod_phase == "Succeeded" or results:
            metrics = json.loads(results) if results else {}
            # an early-stopped pod still exits 0 with its last metrics — the
            # annotation distinguishes pruned from fully-run (earlystop.py)
            phase = ("Pruned" if earlystop.EARLY_STOP_ANNOTATION in annotations
                     else "Succeeded")
            self._set_phase(client, trial, phase, metrics, observations)
        elif pod_phase == "Failed":
            self._set_phase(client, trial, "Failed")
        elif observations:
            # fold the reporter's intermediate observations into status so
            # the StudyJobReconciler's median-stopping pass sees them
            self._set_phase(client, trial, "Running", None, observations)
        return Result()

    @staticmethod
    def _parse_observations(annotations: Dict[str, str]) -> Optional[List[Dict]]:
        raw = annotations.get(earlystop.OBSERVATIONS_ANNOTATION)
        if not raw:
            return None
        try:
            obs = json.loads(raw)
            return obs if isinstance(obs, list) else None
        except ValueError:
            return None

    def _set_phase(
        self,
        client: Client,
        trial: Dict[str, Any],
        phase: str,
        metrics: Optional[Dict] = None,
        observations: Optional[List[Dict]] = None,
    ) -> None:
        fresh = client.get_opt(*self.FOR, apimeta.name_of(trial), apimeta.namespace_of(trial))
        if fresh is None:
            return
        status = {"phase": phase}
        if metrics:
            status["metrics"] = metrics
        if observations:
            status["observations"] = observations
        if fresh.get("status") == status:
            return
        fresh = apimeta.deepcopy(fresh)
        fresh["status"] = status
        client.update_status(fresh)


class InProcessTrialRunner(Reconciler):
    """CI trial executor: runs a real objective function synchronously.

    The CPU analog of a TPU trial pod (the reference's katib e2e is likewise
    CPU-only — SURVEY §4). ``objective_fn(params) -> {metric: value}`` is
    typically a short JAX training run (see kubeflow_tpu.hpo.trials).
    Objectives that accept a ``report_fn`` kwarg get intermediate-metric
    reporting: each report lands in ``status.observations`` (which triggers
    the StudyJobReconciler's median-stopping pass via the OWNS watch), and
    the returned bool tells the objective whether to continue — False once
    the study controller marked this trial with the early-stop annotation.
    """

    FOR = (STUDY_API, "Trial")

    def __init__(self, objective_fn: Callable[..., Dict[str, float]]):
        import inspect

        self.objective_fn = objective_fn
        try:
            self._accepts_report = "report_fn" in inspect.signature(objective_fn).parameters
        except (TypeError, ValueError):
            self._accepts_report = False

    def reconcile(self, client: Client, req: Request) -> Result:
        trial = client.get_opt(*self.FOR, req.name, req.namespace)
        if trial is None or trial.get("status", {}).get("phase") in (
            "Succeeded", "Failed", "Pruned",
        ):
            return Result()
        spec = trial.get("spec", {})
        metric_name = spec.get("objectiveMetricName", "objective")
        observations: List[Dict[str, float]] = []

        def report_fn(step: float, metrics: Dict[str, float]) -> bool:
            fresh = client.get_opt(*self.FOR, req.name, req.namespace)
            value = metrics.get(metric_name)
            if value is not None and fresh is not None:
                observations.append({"step": float(step), "value": float(value)})
                updated = apimeta.deepcopy(fresh)
                updated["status"] = {"phase": "Running", "observations": list(observations)}
                client.update_status(updated)
            # the early-stop mark from a PREVIOUS report interval arrives by
            # now via the study reconciler; one fetch serves both purposes
            stopped = fresh is not None and (
                earlystop.EARLY_STOP_ANNOTATION in apimeta.annotations_of(fresh)
            )
            return not stopped

        try:
            if self._accepts_report:
                metrics = self.objective_fn(spec.get("parameters", {}), report_fn=report_fn)
            else:
                metrics = self.objective_fn(spec.get("parameters", {}))
            fresh = client.get_opt(*self.FOR, req.name, req.namespace)
            was_pruned = fresh is not None and (
                earlystop.EARLY_STOP_ANNOTATION in apimeta.annotations_of(fresh)
            )
            status = {"phase": "Pruned" if was_pruned else "Succeeded", "metrics": metrics}
            if observations:
                status["observations"] = observations
        except Exception as e:  # a failed trial is data, not a controller error
            log.warning("trial %s failed: %s", req.name, e)
            status = {"phase": "Failed", "message": str(e)}
        fresh = client.get_opt(*self.FOR, req.name, req.namespace)
        if fresh is not None and fresh.get("status") != status:
            fresh = apimeta.deepcopy(fresh)
            fresh["status"] = status
            client.update_status(fresh)
        return Result()

def main() -> None:  # python -m kubeflow_tpu.controllers.studyjob
    from ..runtime.bootstrap import run_role

    run_role("studyjob-controller", StudyJobReconciler(), TrialPodRunner())


if __name__ == "__main__":
    main()
