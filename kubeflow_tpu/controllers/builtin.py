"""Cluster-substrate controllers: StatefulSet/Deployment → Pods, fake kubelet.

The reference runs against a real Kubernetes cluster whose controller-manager
and kubelets materialize pods; its envtest suites explicitly *cannot* observe
pods (notebook_controller_bdd_test.go:71-75 — only the API server runs).
This module closes that gap for the TPU build: a minimal in-process
controller-manager + "podlet" that schedules pods onto fake TPU nodes
(nodes advertising ``google.com/tpu`` capacity — the fixture SURVEY.md §4
calls for), so e2e flows (spawn → webhook injection → scheduling → Running)
are testable without a cluster or real chips.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..apiserver.store import Conflict
from ..runtime.manager import Reconciler, Request, Result
from ..runtime.tracing import BIND_TRACEPARENT_ANNOTATION, TRACER
from ..scheduler.gang import POD_GROUP_LABEL, POD_GROUP_SIZE_ANNOTATION, requires_scheduling
from ..tpu.topology import RESOURCE_TPU

POD_OWNER_INDEX = "controller-owner-uid"


def _pod_owner_keys(pod: Dict[str, Any]) -> List[str]:
    uid = (apimeta.controller_owner_of(pod) or {}).get("uid")
    return [uid] if uid else []


class _OwnedPodsMixin:
    """Pods owned by one controller object, via an informer index when the
    reconciler runs under a Manager — the per-reconcile list of EVERY pod in
    the namespace was a top cost in the 500-notebook loadtest profile."""

    #: per-object consecutive-unconverged counts → capped backoff for the
    #: stale-informer insurance requeue (a set that CANNOT converge — e.g.
    #: pods Pending on exhausted TPU capacity — must not poll at 5 Hz
    #: forever; one that just raced the mirror must retry fast).
    def _insurance_requeue(self, key) -> "Result":
        if not hasattr(self, "_unconverged"):
            self._unconverged = {}
        n = self._unconverged.get(key, 0)
        self._unconverged[key] = n + 1
        return Result(requeue_after=min(0.2 * (2 ** min(n, 6)), 5.0))

    def _note_converged(self, key) -> None:
        if hasattr(self, "_unconverged"):
            self._unconverged.pop(key, None)

    def _owned_pods(self, client: Client, namespace: Optional[str], owner_uid: str):
        if self.cache is None:
            return [
                p for p in client.list("v1", "Pod", namespace)
                if (apimeta.controller_owner_of(p) or {}).get("uid") == owner_uid
            ]
        inf = self.cache.informer_for("v1", "Pod")
        inf.add_index(POD_OWNER_INDEX, _pod_owner_keys)
        inf.wait_synced()
        return inf.by_index(POD_OWNER_INDEX, owner_uid)

    @staticmethod
    def _create_pod_tolerant(client: Client, pod: Dict[str, Any]) -> None:
        """Informer reads lag our own writes by one watch delivery; a
        same-name Conflict just means the pod already exists."""
        try:
            client.create(pod)
        except Conflict:
            pass


def _pod_for_template(
    owner: Dict[str, Any], name: str, template: Dict[str, Any], extra_labels: Dict[str, str]
) -> Dict[str, Any]:
    tmpl_meta = template.get("metadata", {})
    labels = dict(tmpl_meta.get("labels") or {})
    labels.update(extra_labels)
    pod = apimeta.new_object(
        "v1",
        "Pod",
        name,
        apimeta.namespace_of(owner),
        labels=labels,
        annotations=dict(tmpl_meta.get("annotations") or {}),
        spec=apimeta.deepcopy(template.get("spec", {})),
    )
    apimeta.set_owner_reference(pod, owner)
    return pod


class StatefulSetReconciler(_OwnedPodsMixin, Reconciler):
    """Materializes ordinal pods with stable hostnames + subdomain DNS —
    exactly the properties the JAX coordinator bootstrap relies on."""

    FOR = ("apps/v1", "StatefulSet")
    OWNS = [("v1", "Pod")]

    def reconcile(self, client: Client, req: Request) -> Result:
        sts = client.get_opt(*self.FOR, req.name, req.namespace)
        if sts is None:
            return Result()
        spec = sts.get("spec", {})
        replicas = spec.get("replicas", 1)
        template = spec.get("template", {})
        service_name = spec.get("serviceName") or req.name
        selector_labels = (spec.get("selector") or {}).get("matchLabels") or {}

        owned = self._owned_pods(client, req.namespace, apimeta.uid_of(sts))
        existing = {apimeta.name_of(p): p for p in owned}
        want_names = [f"{req.name}-{i}" for i in range(replicas)]
        mutated = False
        for i, name in enumerate(want_names):
            if name in existing:
                continue
            pod = _pod_for_template(sts, name, template, selector_labels)
            pod["spec"]["hostname"] = name
            pod["spec"]["subdomain"] = service_name
            pod["metadata"].setdefault("annotations", {})[
                "apps.kubernetes.io/pod-index"
            ] = str(i)
            pod["metadata"].setdefault("labels", {})[
                "statefulset.kubernetes.io/pod-name"
            ] = name
            # Slice pods form a gang: the scheduler binds all `replicas`
            # hosts of this StatefulSet all-or-nothing (scheduler/gang.py).
            pod["metadata"]["labels"].setdefault(POD_GROUP_LABEL, req.name)
            pod["metadata"]["annotations"].setdefault(
                POD_GROUP_SIZE_ANNOTATION, str(replicas)
            )
            self._create_pod_tolerant(client, pod)
            mutated = True
        for name in set(existing) - set(want_names):
            client.delete_opt("v1", "Pod", name, req.namespace)
            mutated = True
        # Pod template drift → recreate (simplified rolling update).
        for name in want_names:
            pod = existing.get(name)
            if pod is None:
                continue
            if _template_drifted(pod["spec"], template.get("spec", {})):
                client.delete_opt("v1", "Pod", name, req.namespace)
                mutated = True

        pods = self._owned_pods(client, req.namespace, apimeta.uid_of(sts))
        ready = sum(1 for p in pods if p.get("status", {}).get("phase") == "Running")
        sts["status"] = {"replicas": len(pods), "readyReplicas": ready, "currentReplicas": len(pods)}
        client.update_status(sts)
        key = (req.namespace, req.name)
        if mutated or ready != replicas or len(pods) != replicas:
            # Not converged (or this pass mutated based on the mirror view):
            # requeue instead of trusting the next watch event to arrive
            # AFTER the informer mirror has applied it. The trigger watch and
            # the informer are independent streams — a reconcile fired by the
            # final pod event of a churn wave can read a mirror that hasn't
            # seen that event yet, write stale status, and (being the last
            # event) never run again. Caught live at 500-notebook churn: pod
            # Running, status stuck at readyReplicas 0. ``mutated`` also
            # covers the drift-delete path, where a stale mirror can make
            # the post-delete recount LOOK converged.
            return self._insurance_requeue(key)
        self._note_converged(key)
        return Result()


def _template_drifted(live_spec: Dict[str, Any], want_spec: Dict[str, Any]) -> bool:
    """Compare the fields the template owns, ignoring admission-injected ones.

    The webhook mutates pods at creation (env/resources/nodeSelector), so a
    naive spec comparison would bounce pods forever. Compare container
    image/command and counts only.
    """
    live_c = live_spec.get("containers") or []
    want_c = want_spec.get("containers") or []
    if len(live_c) != len(want_c):
        return True
    for lc, wc in zip(live_c, want_c):
        for field in ("image", "command", "args", "name"):
            if lc.get(field) != wc.get(field):
                return True
    return False


class DeploymentReconciler(_OwnedPodsMixin, Reconciler):
    """Deployment → pods (no ReplicaSet indirection; tensorboards and web
    apps only need replica maintenance)."""

    FOR = ("apps/v1", "Deployment")
    OWNS = [("v1", "Pod")]

    def reconcile(self, client: Client, req: Request) -> Result:
        dep = client.get_opt(*self.FOR, req.name, req.namespace)
        if dep is None:
            return Result()
        spec = dep.get("spec", {})
        replicas = spec.get("replicas", 1)
        template = spec.get("template", {})
        selector_labels = (spec.get("selector") or {}).get("matchLabels") or {}
        owned = self._owned_pods(client, req.namespace, apimeta.uid_of(dep))
        existing = {apimeta.name_of(p): p for p in owned}
        want_names = [f"{req.name}-{i}" for i in range(replicas)]
        mutated = False
        for name in want_names:
            if name not in existing:
                self._create_pod_tolerant(client, _pod_for_template(dep, name, template, selector_labels))
                mutated = True
        for name in set(existing) - set(want_names):
            client.delete_opt("v1", "Pod", name, req.namespace)
            mutated = True
        pods = self._owned_pods(client, req.namespace, apimeta.uid_of(dep))
        ready = sum(1 for p in pods if p.get("status", {}).get("phase") == "Running")
        dep["status"] = {
            "replicas": len(pods),
            "readyReplicas": ready,
            "availableReplicas": ready,
            "conditions": [
                {
                    "type": "Available",
                    "status": "True" if ready >= replicas else "False",
                    "reason": "MinimumReplicasAvailable" if ready >= replicas else "MinimumReplicasUnavailable",
                }
            ],
        }
        client.update_status(dep)
        key = (req.namespace, req.name)
        if mutated or ready != replicas or len(pods) != replicas:
            # same stale-informer insurance as the StatefulSet reconciler
            return self._insurance_requeue(key)
        self._note_converged(key)
        return Result()


class PodletReconciler(Reconciler):
    """Pure kubelet: runs whatever is bound — placement lives elsewhere.

    Binding (nodeSelector, gang all-or-nothing, chip capacity, quota,
    priority) is the scheduler subsystem's job (``kubeflow_tpu/scheduler/``);
    this reconciler only transitions bound pods to Running. Pods that need
    scheduling (a node exists, or the pod requests ``google.com/tpu``
    chips) are left alone until the scheduler's bind re-triggers this
    reconciler through the pod watch. With zero nodes in the store and no
    TPU ask, pods just run — keeps non-scheduling tests lightweight,
    exactly as before the split.
    """

    FOR = ("v1", "Pod")

    def reconcile(self, client: Client, req: Request) -> Result:
        pod = client.get_opt("v1", "Pod", req.name, req.namespace)
        # Running is steady-state; Succeeded/Failed are terminal — a kubelet
        # never restarts a completed restartPolicy=Never pod (trial pods
        # signal completion exactly this way).
        if pod is None or pod.get("status", {}).get("phase") in ("Running", "Succeeded", "Failed"):
            return Result()
        if not pod.get("spec", {}).get("nodeName"):
            if requires_scheduling(pod, have_nodes=bool(client.list("v1", "Node"))):
                # Unbound and schedulable: the scheduler owns it; its bind
                # update re-triggers this reconciler.
                return Result()
            # No nodes and no TPU request: run in place (unit-test mode).
        return self._start(client, pod)

    def _start(self, client: Client, pod: Dict[str, Any]) -> Result:
        # pod.start joins the gang trace through the scheduler's bind
        # annotation — the critical-path analyzer's post-bind segment.
        with TRACER.span(
            "pod.start",
            traceparent=apimeta.annotations_of(pod).get(
                BIND_TRACEPARENT_ANNOTATION),
            pod=f"{apimeta.namespace_of(pod) or ''}/{apimeta.name_of(pod)}",
            node=str((pod.get("spec") or {}).get("nodeName") or ""),
        ):
            return self._run_pod(client, pod)

    def _run_pod(self, client: Client, pod: Dict[str, Any]) -> Result:
        pod["status"] = {
            "phase": "Running",
            "podIP": "10.1.0.1",
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Ready", "status": "True"},
            ],
            "containerStatuses": [
                {
                    "name": c.get("name", "main"),
                    "ready": True,
                    "restartCount": 0,
                    "state": {"running": {"startedAt": client.store.now()}},
                }
                for c in pod.get("spec", {}).get("containers", [])
            ],
        }
        client.update_status(pod)
        return Result()


def make_tpu_node(name: str, generation: str, topology_label: str, chips: int) -> Dict[str, Any]:
    """Fixture: a GKE-shaped TPU node (SURVEY §4 'fake TPU node fixture')."""
    from ..tpu.topology import ACCELERATORS, NODE_LABEL_ACCELERATOR, NODE_LABEL_TOPOLOGY

    acc = ACCELERATORS[generation]
    node = apimeta.new_object(
        "v1",
        "Node",
        name,
        labels={
            NODE_LABEL_ACCELERATOR: acc.gke_name,
            NODE_LABEL_TOPOLOGY: topology_label,
            "cloud.google.com/gke-nodepool": f"tpu-{generation}-pool",
        },
        spec={"providerID": f"gce://tpu-project/us-central2-b/{name}"},
    )
    node["status"] = {
        "capacity": {RESOURCE_TPU: str(chips), "cpu": "96", "memory": "340Gi"},
        "allocatable": {RESOURCE_TPU: str(chips)},
        "conditions": [{"type": "Ready", "status": "True"}],
    }
    return node

def main() -> None:  # python -m kubeflow_tpu.controllers.builtin (substrate)
    from ..runtime.bootstrap import run_role
    from ..scheduler.core import SchedulerReconciler

    run_role(
        "substrate",
        StatefulSetReconciler(),
        DeploymentReconciler(),
        SchedulerReconciler(),
        PodletReconciler(),
    )


if __name__ == "__main__":
    main()
