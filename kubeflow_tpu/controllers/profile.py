"""Profile controller: the multi-tenancy engine, TPU-quota-aware.

Re-implements the reference profile-controller
(components/profile-controller/controllers/profile_controller.go) for the
TPU platform:

- cluster-scoped ``Profile`` CR → Namespace (owner annotation,
  ``istio-injection: enabled`` label; adoption-conflict produces a Failed
  condition, not a crash — reference :126-191),
- Istio AuthorizationPolicy ``ns-owner-access-istio`` allowing the owner by
  userid header, intra-namespace traffic, and probe paths (:340-438),
- ServiceAccounts ``default-editor``/``default-viewer`` bound to
  ClusterRoles ``kubeflow-edit``/``kubeflow-view`` (:201-217, 474-520),
- owner RoleBinding ``namespaceAdmin`` → ``kubeflow-admin`` (:221-244),
- ResourceQuota ``kf-resource-quota`` from ``spec.resourceQuotaSpec``
  (:245-261) — **the per-namespace TPU chip quota hook**
  (``requests.google.com/tpu``), with a platform default applied when the
  admin configures ``default_tpu_chips``,
- plugin apply/revoke with finalizer-gated teardown (:262-312); the cloud
  IAM plugins (WorkloadIdentity/AwsIam) annotate ServiceAccounts; actual
  cloud API calls are delegated to an injectable ``iam_backend`` so tests
  (and clusters without cloud credentials) run without egress — the same
  separation the reference tests use (plugin_iam_test.go manipulates policy
  JSON without AWS calls).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..runtime.manager import Reconciler, Request, Result
from ..runtime.metrics import METRICS
from ..runtime import reconcile as rh
# Quota constants live with their enforcement point — the scheduler admits
# gangs against this quota at bind time; this controller only writes it.
from ..scheduler.gang import QUOTA_NAME, TPU_QUOTA_KEY  # noqa: F401 (re-export)

log = logging.getLogger("kubeflow_tpu.profile")

PROFILE_API = "kubeflow.org/v1"
OWNER_ANNOTATION = "owner"
FINALIZER = "profile-controller.kubeflow.org/finalizer"
AUTH_POLICY_NAME = "ns-owner-access-istio"

#: ClusterRole name ↔ workgroup role (reference kfam bindings.go:39-46).
ROLE_MAP = {"admin": "kubeflow-admin", "edit": "kubeflow-edit", "view": "kubeflow-view"}


@dataclass
class ProfileConfig:
    userid_header: str = "kubeflow-userid"
    userid_prefix: str = ""
    workload_identity: str = ""  # default GCP SA to bind, if set
    default_tpu_chips: Optional[int] = None  # default per-namespace quota
    # Injectable cloud-IAM backend: (action, plugin_kind, spec, namespace) -> None
    iam_backend: Optional[Callable[[str, str, Dict[str, Any], str], None]] = None

    @classmethod
    def from_env(cls) -> "ProfileConfig":
        import os

        from ..utils import env_flag
        from .iam import CloudIamBackend

        chips = os.environ.get("DEFAULT_TPU_QUOTA_CHIPS", "")
        # ENABLE_CLOUD_IAM=false opts out for clusters without cloud creds;
        # with it on (default), plugin apply/revoke edits real IAM policy
        # documents through the stdlib transports (iam.py).
        backend = CloudIamBackend() if env_flag("ENABLE_CLOUD_IAM", True) else None
        return cls(
            userid_header=os.environ.get("USERID_HEADER", "kubeflow-userid"),
            userid_prefix=os.environ.get("USERID_PREFIX", ""),
            workload_identity=os.environ.get("WORKLOAD_IDENTITY", ""),
            default_tpu_chips=int(chips) if chips else None,
            iam_backend=backend,
        )


class ProfileReconciler(Reconciler):
    FOR = (PROFILE_API, "Profile")
    OWNS = [
        ("v1", "Namespace"),
        ("v1", "ServiceAccount"),
        ("rbac.authorization.k8s.io/v1", "RoleBinding"),
        ("security.istio.io/v1beta1", "AuthorizationPolicy"),
        ("v1", "ResourceQuota"),
    ]

    def __init__(self, config: Optional[ProfileConfig] = None):
        self.config = config or ProfileConfig()

    # -- reconcile -----------------------------------------------------------
    def reconcile(self, client: Client, req: Request) -> Result:
        profile = client.get_opt(PROFILE_API, "Profile", req.name)
        if profile is None:
            return Result()
        METRICS.counter("request_kf", kind="profile").inc()

        md = profile["metadata"]
        if md.get("deletionTimestamp"):
            return self._finalize(client, profile)
        if FINALIZER not in (md.get("finalizers") or []):
            profile = apimeta.deepcopy(profile)
            profile["metadata"].setdefault("finalizers", []).append(FINALIZER)
            profile = client.update(profile)

        try:
            ns_ok = self._reconcile_namespace(client, profile)
            if not ns_ok:
                # Ownership conflict: error condition set; periodic re-check.
                return Result(requeue_after=5.0)
            self._reconcile_auth_policy(client, profile)
            self._reconcile_service_accounts(client, profile)
            self._reconcile_owner_binding(client, profile)
            self._reconcile_quota(client, profile)
            self._apply_plugins(client, profile)
        except Exception as e:
            METRICS.counter("request_kf_failure", kind="profile", severity="major").inc()
            self._set_condition(client, profile, "Failed", str(e))
            raise
        self._set_condition(client, profile, "Successful", "")
        return Result()

    # -- namespace -----------------------------------------------------------
    def _reconcile_namespace(self, client: Client, profile: Dict[str, Any]) -> bool:
        name = apimeta.name_of(profile)
        owner = profile.get("spec", {}).get("owner", {}).get("name", "")
        ns = client.get_opt("v1", "Namespace", name)
        if ns is None:
            ns = apimeta.new_object(
                "v1",
                "Namespace",
                name,
                labels={
                    "istio-injection": "enabled",
                    "app.kubernetes.io/part-of": "kubeflow-profile",
                },
                annotations={OWNER_ANNOTATION: owner},
            )
            apimeta.set_owner_reference(ns, profile)
            client.create(ns)
            return True
        anns = apimeta.annotations_of(ns)
        if OWNER_ANNOTATION not in anns:
            # Adopt: pre-existing namespace without owner (reference :166-183).
            ns = apimeta.deepcopy(ns)
            ns["metadata"].setdefault("annotations", {})[OWNER_ANNOTATION] = owner
            ns["metadata"].setdefault("labels", {})["istio-injection"] = "enabled"
            apimeta.set_owner_reference(ns, profile)
            client.update(ns)
            return True
        if anns.get(OWNER_ANNOTATION) != owner:
            self._set_condition(
                client,
                profile,
                "Failed",
                f"namespace {name} owned by {anns.get(OWNER_ANNOTATION)!r}, not {owner!r}",
            )
            return False
        return True

    # -- istio authz ---------------------------------------------------------
    def _reconcile_auth_policy(self, client: Client, profile: Dict[str, Any]) -> None:
        name = apimeta.name_of(profile)
        owner = profile.get("spec", {}).get("owner", {}).get("name", "")
        header = self.config.userid_header
        principal = f"{self.config.userid_prefix}{owner}"
        policy = apimeta.new_object(
            "security.istio.io/v1beta1",
            "AuthorizationPolicy",
            AUTH_POLICY_NAME,
            name,
            spec={
                "rules": [
                    # Owner by identity header (reference :352-366).
                    {"when": [{"key": f"request.headers[{header}]", "values": [principal]}]},
                    # Intra-namespace traffic (reference :368-377).
                    {"from": [{"source": {"namespaces": [name]}}]},
                    # Health/probe paths (reference :368-383).
                    {"to": [{"operation": {"paths": ["/healthz", "/metrics", "/wait-for-drain"]}}]},
                ]
            },
        )
        rh.reconcile_object(client, policy, profile)

    # -- rbac ----------------------------------------------------------------
    def _reconcile_service_accounts(self, client: Client, profile: Dict[str, Any]) -> None:
        ns = apimeta.name_of(profile)
        for sa_name, role in (("default-editor", "kubeflow-edit"), ("default-viewer", "kubeflow-view")):
            sa = apimeta.new_object("v1", "ServiceAccount", sa_name, ns)
            apimeta.set_owner_reference(sa, profile)
            existing = client.get_opt("v1", "ServiceAccount", sa_name, ns)
            if existing is None:
                client.create(sa)
            binding = apimeta.new_object(
                "rbac.authorization.k8s.io/v1",
                "RoleBinding",
                sa_name,
                ns,
                roleRef={"apiGroup": "rbac.authorization.k8s.io", "kind": "ClusterRole", "name": role},
                subjects=[{"kind": "ServiceAccount", "name": sa_name, "namespace": ns}],
            )
            rh.reconcile_object(client, binding, profile)

    def _reconcile_owner_binding(self, client: Client, profile: Dict[str, Any]) -> None:
        ns = apimeta.name_of(profile)
        owner = profile.get("spec", {}).get("owner", {})
        binding = apimeta.new_object(
            "rbac.authorization.k8s.io/v1",
            "RoleBinding",
            "namespaceAdmin",
            ns,
            annotations={
                "role": "admin",
                "user": owner.get("name", ""),
            },
            roleRef={"apiGroup": "rbac.authorization.k8s.io", "kind": "ClusterRole", "name": ROLE_MAP["admin"]},
            subjects=[owner or {"kind": "User", "name": ""}],
        )
        rh.reconcile_object(client, binding, profile)

    # -- quota (the TPU hook) ------------------------------------------------
    def _reconcile_quota(self, client: Client, profile: Dict[str, Any]) -> None:
        ns = apimeta.name_of(profile)
        spec = apimeta.deepcopy(profile.get("spec", {}).get("resourceQuotaSpec") or {})
        if self.config.default_tpu_chips is not None:
            spec.setdefault("hard", {}).setdefault(TPU_QUOTA_KEY, str(self.config.default_tpu_chips))
        if not spec.get("hard"):
            # No quota requested: remove a previously-applied one.
            client.delete_opt("v1", "ResourceQuota", QUOTA_NAME, ns)
            return
        quota = apimeta.new_object("v1", "ResourceQuota", QUOTA_NAME, ns, spec=spec)
        rh.reconcile_object(client, quota, profile)

    # -- plugins -------------------------------------------------------------
    def _plugins_of(self, profile: Dict[str, Any]) -> List[Dict[str, Any]]:
        plugins = list(profile.get("spec", {}).get("plugins") or [])
        if self.config.workload_identity and not any(
            p.get("kind") == "WorkloadIdentity" for p in plugins
        ):
            # PatchDefaultPluginSpec (reference :592-615).
            plugins.append(
                {"kind": "WorkloadIdentity", "spec": {"gcpServiceAccount": self.config.workload_identity}}
            )
        return plugins

    def _apply_plugins(self, client: Client, profile: Dict[str, Any]) -> None:
        ns = apimeta.name_of(profile)
        for plugin in self._plugins_of(profile):
            kind = plugin.get("kind", "")
            spec = plugin.get("spec") or {}
            if kind == "WorkloadIdentity":
                self._annotate_ksa(
                    client, ns, "default-editor",
                    {"iam.gke.io/gcp-service-account": spec.get("gcpServiceAccount", "")},
                )
            elif kind == "AwsIamForServiceAccount":
                self._annotate_ksa(
                    client, ns, "default-editor",
                    {"eks.amazonaws.com/role-arn": spec.get("awsIamRole", "")},
                )
            else:
                raise ValueError(f"unknown plugin kind {kind!r}")
            if self.config.iam_backend:
                self.config.iam_backend("apply", kind, spec, ns)

    def _revoke_plugins(self, client: Client, profile: Dict[str, Any]) -> None:
        ns = apimeta.name_of(profile)
        for plugin in self._plugins_of(profile):
            kind = plugin.get("kind", "")
            spec = plugin.get("spec") or {}
            if self.config.iam_backend:
                try:
                    self.config.iam_backend("revoke", kind, spec, ns)
                except Exception:
                    log.exception("plugin revoke failed (idempotent; continuing)")

    def _annotate_ksa(self, client: Client, ns: str, sa_name: str, annotations: Dict[str, str]) -> None:
        sa = client.get_opt("v1", "ServiceAccount", sa_name, ns)
        if sa is None:
            return
        current = apimeta.annotations_of(sa)
        if all(current.get(k) == v for k, v in annotations.items()):
            return
        sa = apimeta.deepcopy(sa)
        sa["metadata"].setdefault("annotations", {}).update(annotations)
        client.update(sa)

    # -- teardown ------------------------------------------------------------
    def _finalize(self, client: Client, profile: Dict[str, Any]) -> Result:
        self._revoke_plugins(client, profile)
        client.delete_opt("v1", "Namespace", apimeta.name_of(profile))
        profile = apimeta.deepcopy(profile)
        finalizers = profile["metadata"].get("finalizers") or []
        if FINALIZER in finalizers:
            profile["metadata"]["finalizers"] = [f for f in finalizers if f != FINALIZER]
            client.update(profile)
        return Result()

    # -- status --------------------------------------------------------------
    def _set_condition(self, client: Client, profile: Dict[str, Any], type_: str, message: str) -> None:
        fresh = client.get_opt(PROFILE_API, "Profile", apimeta.name_of(profile))
        if fresh is None:
            return
        conditions = [{"type": type_, "status": "True", "message": message}]
        if (fresh.get("status") or {}).get("conditions") == conditions:
            return
        fresh = apimeta.deepcopy(fresh)
        fresh["status"] = {"conditions": conditions}
        client.update_status(fresh)

def main() -> None:  # python -m kubeflow_tpu.controllers.profile
    from ..runtime.bootstrap import run_role

    run_role("profile-controller", ProfileReconciler(ProfileConfig.from_env()))


if __name__ == "__main__":
    main()
