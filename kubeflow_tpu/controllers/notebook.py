"""Notebook controller: TPU-slice-aware workload reconciliation.

Re-implements the reference notebook-controller
(components/notebook-controller/controllers/notebook_controller.go) with the
structural changes the TPU re-targeting demands (SURVEY.md §7 step 3):

- ``replicas = num_hosts(topology)`` instead of the reference's hard-coded 1
  (notebook_controller.go:302): a multi-host slice notebook is one
  StatefulSet with one pod per TPU VM host.
- A *headless* governing Service named after the notebook provides the
  stable per-pod DNS the JAX coordinator bootstrap needs; a separate
  ClusterIP Service ``<name>-http`` carries UI traffic (the reference's
  single ClusterIP Service — generateService :368-395 — cannot provide
  per-pod A records).
- Culling aggregates idleness across hosts and stops the whole slice
  (annotation ``kubeflow-resource-stopped`` scaling replicas→0, same
  mechanism as pkg/culler/culler.go:37,91-135).
- Event mirroring: pod/StatefulSet events re-emitted onto the Notebook CR
  (notebook_controller.go:90-109, nbNameFromInvolvedObject :539).
- Prometheus metrics keep the reference names (pkg/metrics/metrics.go:13-60)
  plus TPU chip gauges.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..runtime.manager import Reconciler, Request, Result
from ..runtime.metrics import METRICS
from ..runtime import reconcile as rh
from ..tpu.env import JAX_COORDINATOR_PORT
from ..tpu.topology import SliceTopology, parse_topology

STOP_ANNOTATION = "kubeflow-resource-stopped"  # reference: culler.go:37
LAST_ACTIVITY_ANNOTATION = "notebooks.kubeflow.org/last-activity"
HTTP_REWRITE_ANNOTATION = "notebooks.kubeflow.org/http-rewrite-uri"
HEADERS_ANNOTATION = "notebooks.kubeflow.org/http-headers-request-set"
NOTEBOOK_NAME_LABEL = "notebook-name"
DEFAULT_CONTAINER_PORT = 8888
MIRROR_MEMO_CAP = 4096  # FIFO bound on the mirrored-event dedupe memo
DEFAULT_FSGROUP = 100


@dataclass
class NotebookConfig:
    """Env-knob surface of the reference controller (main.go + culler.go:24-27)."""

    use_istio: bool = True
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    enable_culling: bool = False
    idle_time_minutes: int = 1440
    culling_check_period_minutes: int = 1
    add_fsgroup: bool = True
    # Idleness prober: (notebook) -> last_activity epoch seconds or None.
    # Production (from_env) defaults to the per-host HTTP prober over Jupyter's
    # /api/status (culler.go:138-169, culler.py); tests inject a fake.
    activity_prober: Optional[Callable[[Dict[str, Any]], Optional[float]]] = None

    @classmethod
    def from_env(cls) -> "NotebookConfig":
        """The reference's env knob set (culler.go:24-27, notebook main.go)."""
        import os

        from ..utils import env_flag
        from .culler import HttpActivityProber

        cluster_domain = os.environ.get("CLUSTER_DOMAIN", "cluster.local")
        return cls(
            use_istio=env_flag("USE_ISTIO", True),
            istio_gateway=os.environ.get("ISTIO_GATEWAY", "kubeflow/kubeflow-gateway"),
            cluster_domain=cluster_domain,
            enable_culling=env_flag("ENABLE_CULLING", False),
            idle_time_minutes=int(os.environ.get("IDLE_TIME", "1440")),
            culling_check_period_minutes=int(os.environ.get("CULLING_CHECK_PERIOD", "1")),
            add_fsgroup=env_flag("ADD_FSGROUP", True),
            activity_prober=HttpActivityProber(cluster_domain=cluster_domain),
        )


def tpu_topology_of(notebook: Dict[str, Any]) -> Optional[SliceTopology]:
    tpu = notebook.get("spec", {}).get("tpu")
    if not tpu:
        return None
    return parse_topology(tpu["generation"], tpu["topology"])


def is_stopped(obj: Dict[str, Any]) -> bool:
    return STOP_ANNOTATION in apimeta.annotations_of(obj)


class NotebookReconciler(Reconciler):
    FOR = ("kubeflow.org/v1beta1", "Notebook")
    OWNS = [
        ("apps/v1", "StatefulSet"),
        ("v1", "Service"),
        ("networking.istio.io/v1beta1", "VirtualService"),
    ]

    def __init__(self, config: Optional[NotebookConfig] = None):
        self.config = config or NotebookConfig()
        # Mirrored-event keys also tracked locally: the informer cache lags
        # the write we just made by one watch delivery, so two back-to-back
        # reconciles would double-mirror without this. Insertion-ordered and
        # FIFO-capped (plus cleared per notebook on delete) so a long-lived
        # controller can't grow it per distinct (reason, message) forever;
        # an evicted key at worst re-mirrors one event the informer already
        # dedupes once its cache catches up.
        self._mirrored_keys: Dict[tuple, None] = {}
        # Lazily-built incremental running-notebook sets per namespace.
        self._running_by_ns: Optional[Dict[str, set]] = None

    def watches(self):
        def map_pod(pod: Dict[str, Any]) -> List[Request]:
            nb = apimeta.labels_of(pod).get(NOTEBOOK_NAME_LABEL)
            return [Request(apimeta.namespace_of(pod), nb)] if nb else []

        def map_event(ev: Dict[str, Any]) -> List[Request]:
            name = _nb_name_from_involved_object(ev)
            if name:
                return [Request(ev.get("involvedObject", {}).get("namespace"), name)]
            return []

        return [(("v1", "Pod"), map_pod), (("v1", "Event"), map_event)]

    # -- reconcile -----------------------------------------------------------
    def reconcile(self, client: Client, req: Request) -> Result:
        nb = client.get_opt(*self.FOR, req.name, req.namespace)
        if nb is None:
            for key in [k for k in self._mirrored_keys
                        if k[0] == req.namespace and k[1] == req.name]:
                del self._mirrored_keys[key]
            return Result()

        self._mirror_child_events(client, nb)

        try:
            sts = self._generate_statefulset(nb)
        except (ValueError, KeyError, TypeError, AttributeError) as e:
            # Invalid spec (bad tpu topology etc.): terminal, not retryable —
            # surface it instead of crash-looping (the reference validates at
            # spawn time; CRs can still arrive malformed via kubectl).
            METRICS.counter("notebook_create_failed_total").inc()
            nb = apimeta.deepcopy(nb)
            nb["status"] = {
                "conditions": [
                    {"type": "Failed", "status": "True", "reason": "InvalidSpec", "message": str(e)}
                ]
            }
            client.update_status(nb)
            existing = [
                ev
                for ev in client.list("v1", "Event", req.namespace)
                if ev.get("involvedObject", {}).get("name") == req.name
                and ev.get("reason") == "InvalidSpec"
            ]
            if not existing:
                client.emit_event(nb, "InvalidSpec", str(e), type_="Warning")
            return Result()
        live_sts = client.get_opt("apps/v1", "StatefulSet", req.name, req.namespace)
        created = live_sts is None
        rh.reconcile_object(client, sts, nb)
        if created:
            METRICS.counter("notebook_create_total").inc()

        rh.reconcile_object(client, self._generate_headless_service(nb), nb)
        rh.reconcile_object(client, self._generate_http_service(nb), nb)
        if self.config.use_istio:
            rh.reconcile_object(client, self._generate_virtual_service(nb), nb)

        self._gang_recovery(client, nb)
        self._update_status(client, nb)
        self._update_running_gauge(client, req.namespace)

        if self.config.enable_culling and not is_stopped(nb):
            return self._check_culling(client, nb)
        return Result()

    # -- generators ----------------------------------------------------------
    def _generate_statefulset(self, nb: Dict[str, Any]) -> Dict[str, Any]:
        name = apimeta.name_of(nb)
        ns = apimeta.namespace_of(nb)
        topo = tpu_topology_of(nb)
        replicas = 0 if is_stopped(nb) else (topo.num_hosts if topo else 1)

        template = apimeta.deepcopy(nb.get("spec", {}).get("template") or {"spec": {"containers": [{}]}})
        pod_meta = template.setdefault("metadata", {})
        pod_labels = pod_meta.setdefault("labels", {})
        # Copy notebook labels onto pods — PodDefault matching depends on it
        # (reference: notebook_controller.go:328-332).
        pod_labels.update(apimeta.labels_of(nb))
        pod_labels[NOTEBOOK_NAME_LABEL] = name
        pod_labels["app"] = name
        pod_labels["statefulset"] = name  # must cover the selector below

        spec = template.setdefault("spec", {})
        # Interactive slices outrank batch work: the gang scheduler may
        # preempt lower classes (trials) to bind a notebook (scheduler/gang.py).
        spec.setdefault("priorityClassName", "notebook")
        containers = spec.setdefault("containers", [{}])
        if not containers:
            containers.append({})
        first = containers[0]
        first.setdefault("name", name)
        first.setdefault("workingDir", "/home/jovyan")
        ports = first.setdefault("ports", [])
        if not ports:
            ports.append(
                {"containerPort": DEFAULT_CONTAINER_PORT, "name": "notebook-port", "protocol": "TCP"}
            )
        env = first.setdefault("env", [])
        if not any(e.get("name") == "NB_PREFIX" for e in env):
            env.append({"name": "NB_PREFIX", "value": f"/notebook/{ns}/{name}"})
        if self.config.add_fsgroup:
            spec.setdefault("securityContext", {}).setdefault("fsGroup", DEFAULT_FSGROUP)

        return apimeta.new_object(
            "apps/v1",
            "StatefulSet",
            name,
            ns,
            spec={
                "replicas": replicas,
                "serviceName": name,  # headless governing service = per-pod DNS
                "selector": {"matchLabels": {"statefulset": name, NOTEBOOK_NAME_LABEL: name}},
                "template": template,
                "podManagementPolicy": "Parallel",  # gang-start all slice hosts
            },
        )

    def _generate_headless_service(self, nb: Dict[str, Any]) -> Dict[str, Any]:
        """Worker rendezvous: clusterIP None + coordinator port; publishes
        not-ready addresses so worker 0 is resolvable before Ready."""
        name = apimeta.name_of(nb)
        return apimeta.new_object(
            "v1",
            "Service",
            name,
            apimeta.namespace_of(nb),
            spec={
                "clusterIP": "None",
                "publishNotReadyAddresses": True,
                "selector": {NOTEBOOK_NAME_LABEL: name},
                "ports": [
                    {"name": "jax-coordinator", "port": JAX_COORDINATOR_PORT, "protocol": "TCP"},
                    {"name": f"http-{name}", "port": 80, "targetPort": DEFAULT_CONTAINER_PORT},
                ],
            },
        )

    def _generate_http_service(self, nb: Dict[str, Any]) -> Dict[str, Any]:
        """UI traffic: ClusterIP, port name http-<name> for Istio RBAC
        (reference: generateService :368-395, port naming :386)."""
        name = apimeta.name_of(nb)
        return apimeta.new_object(
            "v1",
            "Service",
            f"{name}-http",
            apimeta.namespace_of(nb),
            spec={
                "type": "ClusterIP",
                "selector": {NOTEBOOK_NAME_LABEL: name, "statefulset.kubernetes.io/pod-name": f"{name}-0"},
                "ports": [
                    {"name": f"http-{name}", "port": 80, "targetPort": DEFAULT_CONTAINER_PORT, "protocol": "TCP"}
                ],
            },
        )

    def _generate_virtual_service(self, nb: Dict[str, Any]) -> Dict[str, Any]:
        """reference: generateVirtualService :401-496."""
        name = apimeta.name_of(nb)
        ns = apimeta.namespace_of(nb)
        prefix = f"/notebook/{ns}/{name}/"
        annotations = apimeta.annotations_of(nb)
        rewrite = annotations.get(HTTP_REWRITE_ANNOTATION, prefix)
        vs = apimeta.new_object(
            "networking.istio.io/v1beta1",
            "VirtualService",
            f"notebook-{ns}-{name}",
            ns,
            spec={
                "hosts": [self.config.istio_host],
                "gateways": [self.config.istio_gateway],
                "http": [
                    {
                        "match": [{"uri": {"prefix": prefix}}],
                        "rewrite": {"uri": rewrite},
                        "route": [
                            {
                                "destination": {
                                    "host": f"{name}-http.{ns}.svc.{self.config.cluster_domain}",
                                    "port": {"number": 80},
                                }
                            }
                        ],
                        "timeout": "300s",
                    }
                ],
            },
        )
        headers = annotations.get(HEADERS_ANNOTATION)
        if headers:
            import json

            vs["spec"]["http"][0]["headers"] = {"request": {"set": json.loads(headers)}}
        return vs

    # -- gang recovery -------------------------------------------------------
    def _gang_recovery(self, client: Client, nb: Dict[str, Any]) -> None:
        """Slice atomicity (SURVEY §7 hard part — no reference analog): a
        multi-host JAX program is all-or-nothing; once one host fails, the
        surviving workers are wedged in dead collectives. Restart the WHOLE
        gang: delete every pod of the slice so the StatefulSet recreates them
        together and `jax.distributed` re-initializes across fresh workers —
        the control-plane half of elastic recovery (workload-side resume
        comes from checkpoints on the PVC home dir)."""
        topo = tpu_topology_of(nb)
        if topo is None or topo.num_hosts <= 1 or is_stopped(nb):
            return
        name, ns = apimeta.name_of(nb), apimeta.namespace_of(nb)
        # Server-side selector: don't pull the namespace's whole pod list
        # over the REST boundary every reconcile.
        pods = client.list("v1", "Pod", ns, label_selector={NOTEBOOK_NAME_LABEL: name})
        failed = [p for p in pods if p.get("status", {}).get("phase") == "Failed"]
        if not failed:
            return
        for p in pods:
            client.delete_opt("v1", "Pod", apimeta.name_of(p), ns)
        METRICS.counter("notebook_slice_recovery_total").inc()
        client.emit_event(
            nb,
            "SliceRecovery",
            f"host(s) {', '.join(apimeta.name_of(p) for p in failed)} failed; "
            f"restarting all {topo.num_hosts} hosts of the {topo.generation} "
            f"{topo.label} slice together",
            type_="Warning",
        )

    # -- status / events -----------------------------------------------------
    def _update_status(self, client: Client, nb: Dict[str, Any]) -> None:
        name, ns = apimeta.name_of(nb), apimeta.namespace_of(nb)
        sts = client.get_opt("apps/v1", "StatefulSet", name, ns)
        ready = (sts or {}).get("status", {}).get("readyReplicas", 0)
        pod0 = client.get_opt("v1", "Pod", f"{name}-0", ns)
        container_state: Dict[str, Any] = {}
        conditions: List[Dict[str, Any]] = []
        if pod0 is not None:
            for cs in pod0.get("status", {}).get("containerStatuses", []):
                if cs.get("name") in (name, pod0["spec"].get("containers", [{}])[0].get("name")):
                    container_state = cs.get("state", {})
                    break
            else:
                statuses = pod0.get("status", {}).get("containerStatuses", [])
                if statuses:
                    container_state = statuses[0].get("state", {})
        topo = tpu_topology_of(nb)
        status = {
            "readyReplicas": ready,
            "containerState": container_state,
            "conditions": conditions,
        }
        if topo is not None:
            status["tpu"] = {
                "topology": topo.label,
                "generation": topo.generation,
                "numHosts": topo.num_hosts,
                "numChips": topo.num_chips,
                "readyHosts": ready,
            }
        if nb.get("status") != status:
            nb = apimeta.deepcopy(nb)
            nb["status"] = status
            client.update_status(nb)

    EVENT_INDEX = "notebook-events"

    def _events_for(self, client: Client, ns: str, name: str) -> List[Dict[str, Any]]:
        """Events touching one notebook: informer index keyed by notebook
        (reference reads through shared informers the same way —
        access-management/kfam/api_default.go:71-75). Without a manager
        (unit tests) fall back to a direct list."""
        if self.cache is None:
            return [
                e for e in client.list("v1", "Event", ns)
                if _nb_name_from_involved_object(e) == name
                or (e.get("involvedObject", {}).get("kind") == "Notebook"
                    and e.get("involvedObject", {}).get("name") == name)
            ]
        inf = self.cache.informer_for("v1", "Event")
        inf.add_index(self.EVENT_INDEX, _event_notebook_keys)
        inf.wait_synced()
        return inf.by_index(self.EVENT_INDEX, f"{ns}/{name}")

    def _mirror_child_events(self, client: Client, nb: Dict[str, Any]) -> None:
        """Re-emit pod/sts events on the Notebook (reference :90-109)."""
        name, ns = apimeta.name_of(nb), apimeta.namespace_of(nb)
        events = self._events_for(client, ns, name)
        mirrored = {
            (ns, name, e.get("reason"), e.get("message"))
            for e in events
            if e.get("involvedObject", {}).get("kind") == "Notebook"
            and e.get("involvedObject", {}).get("name") == name
        } | self._mirrored_keys.keys()
        for ev in events:
            inv = ev.get("involvedObject", {})
            if inv.get("kind") not in ("Pod", "StatefulSet"):
                continue
            if _nb_name_from_involved_object(ev) != name:
                continue
            if ev.get("type") != "Warning":
                continue
            key = (ns, name, ev.get("reason"), ev.get("message"))
            if key in mirrored:
                continue
            client.emit_event(nb, ev.get("reason", ""), ev.get("message", ""), type_="Warning")
            mirrored.add(key)
            self._mirrored_keys[key] = None
            while len(self._mirrored_keys) > MIRROR_MEMO_CAP:
                del self._mirrored_keys[next(iter(self._mirrored_keys))]

    def _update_running_gauge(self, client: Client, namespace: Optional[str]) -> None:
        if self.cache is None:  # no manager: direct scan (unit-test path)
            running = sum(1 for sts in client.list("apps/v1", "StatefulSet", namespace)
                          if _is_running_notebook_sts(sts))
            METRICS.gauge("notebook_running", namespace=namespace or "").set(running)
            return
        # Incremental: a handler on the StatefulSet informer maintains the
        # per-namespace running set; each reconcile reads one dict entry
        # instead of scanning every StatefulSet (the O(cluster) list the
        # reference's metrics collector does — pkg/metrics/metrics.go:82-99).
        if self._running_by_ns is None:
            self._running_by_ns = {}
            tracker = self._running_by_ns

            def track(event_type: str, sts: Dict[str, Any]) -> None:
                sns = apimeta.namespace_of(sts)
                key = apimeta.name_of(sts)
                members = tracker.setdefault(sns, set())
                if event_type != "DELETED" and _is_running_notebook_sts(sts):
                    members.add(key)
                else:
                    members.discard(key)
                METRICS.gauge("notebook_running", namespace=sns or "").set(len(members))

            inf = self.cache.informer_for("apps/v1", "StatefulSet")
            inf.add_event_handler(track)
            inf.wait_synced()
            for sts in inf.list():
                track("ADDED", sts)
        METRICS.gauge("notebook_running", namespace=namespace or "").set(
            len(self._running_by_ns.get(namespace, set()))
        )

    # -- culling -------------------------------------------------------------
    def _check_culling(self, client: Client, nb: Dict[str, Any]) -> Result:
        period = self.config.culling_check_period_minutes * 60.0
        prober = self.config.activity_prober
        if prober is None:
            return Result(requeue_after=period)
        last_activity = prober(nb)
        now = time.time()
        if last_activity is None:
            return Result(requeue_after=period)
        idle_seconds = now - last_activity
        if idle_seconds >= self.config.idle_time_minutes * 60.0:
            # Re-fetch: _update_status may have bumped resourceVersion earlier
            # in this pass, and the stale copy would Conflict on update.
            fresh = client.get_opt("kubeflow.org/v1beta1", "Notebook", apimeta.name_of(nb), apimeta.namespace_of(nb))
            if fresh is None:
                return Result()
            nb = apimeta.deepcopy(fresh)
            anns = nb["metadata"].setdefault("annotations", {})
            anns[STOP_ANNOTATION] = client.store.now()
            client.update(nb)
            METRICS.counter("notebook_culling_total").inc()
            METRICS.gauge("last_notebook_culling_timestamp_seconds").set(now)
            client.emit_event(nb, "Culling", f"idle for {idle_seconds:.0f}s; stopping", type_="Normal")
            return Result()
        return Result(requeue_after=period)


def _is_running_notebook_sts(sts: Dict[str, Any]) -> bool:
    return (
        NOTEBOOK_NAME_LABEL in (sts.get("spec", {}).get("selector", {}).get("matchLabels") or {})
        and sts.get("status", {}).get("readyReplicas", 0) > 0
    )


def _event_notebook_keys(ev: Dict[str, Any]) -> List[str]:
    """Index keys ``<ns>/<notebook>`` for an Event: direct Notebook events
    and Pod/StatefulSet child events both land in the same bucket."""
    inv = ev.get("involvedObject", {})
    ns = inv.get("namespace") or apimeta.namespace_of(ev)
    if inv.get("kind") == "Notebook":
        return [f"{ns}/{inv.get('name')}"]
    nb = _nb_name_from_involved_object(ev)
    return [f"{ns}/{nb}"] if nb else []


def _nb_name_from_involved_object(ev: Dict[str, Any]) -> Optional[str]:
    """Map pod/sts event → notebook name (reference: nbNameFromInvolvedObject
    :539 — strips the ordinal suffix from StatefulSet pod names)."""
    inv = ev.get("involvedObject", {})
    name = inv.get("name", "")
    kind = inv.get("kind")
    if kind == "StatefulSet":
        return name or None
    if kind == "Pod":
        base, dash, ordinal = name.rpartition("-")
        if dash and ordinal.isdigit():
            return base
    return None

def main() -> None:  # python -m kubeflow_tpu.controllers.notebook
    from ..runtime.bootstrap import run_role

    run_role("notebook-controller", NotebookReconciler(NotebookConfig.from_env()))


if __name__ == "__main__":
    main()
