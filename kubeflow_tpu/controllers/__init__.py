from .builtin import DeploymentReconciler, PodletReconciler, StatefulSetReconciler  # noqa: F401
from .notebook import NotebookReconciler, NotebookConfig  # noqa: F401
