"""Production idleness prober for notebook culling.

The reference culler polls each notebook's Jupyter server over HTTP —
``http://<nb>.<ns>.svc.<domain>/notebook/<ns>/<nb>/api/status`` — and parses
the ``last_activity`` timestamp out of the JSON body
(components/notebook-controller/pkg/culler/culler.go:138-189). The TPU
re-targeting changes one thing structurally: a slice notebook is *multi-host*
(one Jupyter kernel host per TPU VM), so idleness must aggregate across every
host of the slice — the slice is idle only if ALL hosts are idle, i.e. the
slice's last activity is the max over per-host last activities (SURVEY.md §7
"culling a multi-host notebook" hard part).

Unreachable hosts are treated as "cannot determine" → the prober returns
``None`` and the controller requeues without culling, exactly as the
reference refuses to cull when the status endpoint errors
(culler.go:145-168).
"""

from __future__ import annotations

import datetime
import json
import logging
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

from ..api import meta as apimeta

log = logging.getLogger(__name__)

#: Jupyter container port probed directly through per-pod headless DNS.
#: (The reference probes the ClusterIP Service on :80 — culler.go:141-143 —
#: but per-host probing must bypass the service VIP to reach each host.)
NOTEBOOK_PORT = 8888

DEFAULT_TIMEOUT_SECONDS = 5.0


def parse_last_activity(body: bytes | str) -> Optional[float]:
    """Parse Jupyter's ``/api/status`` JSON → epoch seconds of last activity.

    The reference parses ``{"last_activity": "2006-01-02T15:04:05Z"}`` with a
    fixed layout (culler.go:171-189); Jupyter emits RFC3339 with optional
    fractional seconds, so accept both.
    """
    try:
        doc = json.loads(body)
    except (ValueError, TypeError):
        return None
    stamp = doc.get("last_activity") if isinstance(doc, dict) else None
    if not isinstance(stamp, str):
        return None
    text = stamp.strip()
    if text.endswith("Z"):
        text = text[:-1] + "+00:00"
    try:
        parsed = datetime.datetime.fromisoformat(text)
    except ValueError:
        return None
    if parsed.tzinfo is None:
        parsed = parsed.replace(tzinfo=datetime.timezone.utc)
    return parsed.timestamp()


def _default_http_get(url: str, timeout: float) -> Optional[bytes]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:  # noqa: S310
            if resp.status != 200:
                return None
            return resp.read()
    except (urllib.error.URLError, OSError, ValueError):
        return None


class HttpActivityProber:
    """Default ``NotebookConfig.activity_prober``: probe every slice host.

    Called with the Notebook CR dict; returns the epoch seconds of the
    *slice-wide* last activity (max across hosts), or ``None`` when idleness
    cannot be determined (any host unreachable / unparseable).

    ``url_for`` is injectable for tests and unusual network layouts:
    ``(notebook, host_index) -> url``. The default builds the per-pod
    headless-service DNS name ``<name>-<i>.<name>.<ns>.svc.<domain>`` and the
    reference's status path ``/notebook/<ns>/<name>/api/status``
    (culler.go:141-143).
    """

    def __init__(
        self,
        cluster_domain: str = "cluster.local",
        timeout: float = DEFAULT_TIMEOUT_SECONDS,
        url_for: Optional[Callable[[Dict[str, Any], int], str]] = None,
        http_get: Optional[Callable[[str, float], Optional[bytes]]] = None,
    ):
        self.cluster_domain = cluster_domain
        self.timeout = timeout
        self._url_for = url_for or self._default_url_for
        self._http_get = http_get or _default_http_get

    def _default_url_for(self, nb: Dict[str, Any], host: int) -> str:
        name = apimeta.name_of(nb)
        ns = apimeta.namespace_of(nb)
        pod_dns = f"{name}-{host}.{name}.{ns}.svc.{self.cluster_domain}"
        return f"http://{pod_dns}:{NOTEBOOK_PORT}/notebook/{ns}/{name}/api/status"

    def _num_hosts(self, nb: Dict[str, Any]) -> int:
        from .notebook import tpu_topology_of

        topo = tpu_topology_of(nb)
        return topo.num_hosts if topo else 1

    def _probe_one(self, nb: Dict[str, Any], host: int) -> Optional[float]:
        url = self._url_for(nb, host)
        body = self._http_get(url, self.timeout)
        if body is None:
            log.debug("culling probe unreachable: %s", url)
            return None
        stamp = parse_last_activity(body)
        if stamp is None:
            log.debug("culling probe unparseable: %s", url)
        return stamp

    def __call__(self, nb: Dict[str, Any]) -> Optional[float]:
        n = self._num_hosts(nb)
        if n == 1:
            return self._probe_one(nb, 0)
        # Probe hosts concurrently: this runs on the controller's reconcile
        # worker, so a big slice with unreachable hosts must cost ~one
        # timeout, not num_hosts stacked timeouts.
        with ThreadPoolExecutor(max_workers=min(n, 16), thread_name_prefix="cull-probe") as pool:
            activities = list(pool.map(lambda h: self._probe_one(nb, h), range(n)))
        if any(a is None for a in activities):
            return None
        # Idle only if ALL hosts are idle: the most recent activity anywhere
        # on the slice is the slice's last activity.
        return max(activities)
