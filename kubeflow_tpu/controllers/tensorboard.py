"""Tensorboard controller: log-visualization workloads (incl. JAX profiles).

Re-implements the reference tensorboard-controller
(components/tensorboard-controller/controllers/tensorboard_controller.go):
``Tensorboard`` CR with ``spec.logspath`` → Deployment + Service +
VirtualService; status from Deployment conditions (:117-140).

- ``pvc://<name>[/<subpath>]`` mounts the PVC (:152-227),
- ``gs://...`` paths mount the GCP credential secret ``user-gcp-sa``,
- RWO co-scheduling: when ``rwo_pvc_scheduling`` is on and the PVC is
  ReadWriteOnce, pod affinity pins the viewer onto the node where the pod
  already mounting it runs (:190-215, 437-447).

TPU addition: the deployment serves TensorBoard with the profile plugin so
JAX/XLA device traces captured by ``kubeflow_tpu.training`` land here — the
platform's tracing story (SURVEY.md §5 'tracing: green-field').
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from ..api import meta as apimeta
from ..apiserver.client import Client
from ..runtime.manager import Reconciler, Request, Result
from ..runtime import reconcile as rh

TB_API = "tensorboard.kubeflow.org/v1alpha1"
# TensorBoard + JAX profile plugin (images/tensorboard-jax/) — the TPU-native
# replacement for the reference's tensorflow/tensorflow:2.5.1 deployment
# (tensorboard_controller.go generateDeployment): JAX scalars + profiler
# traces need the xprof plugin, not the TF runtime.
DEFAULT_IMAGE = "kubeflow-tpu/tensorboard-jax:latest"


@dataclass
class TensorboardConfig:
    use_istio: bool = True
    istio_gateway: str = "kubeflow/kubeflow-gateway"
    istio_host: str = "*"
    cluster_domain: str = "cluster.local"
    rwo_pvc_scheduling: bool = False
    image: str = DEFAULT_IMAGE

    @classmethod
    def from_env(cls) -> "TensorboardConfig":
        import os

        from ..utils import env_flag

        return cls(
            rwo_pvc_scheduling=env_flag("RWO_PVC_SCHEDULING"),
            image=os.environ.get("TENSORBOARD_IMAGE", DEFAULT_IMAGE),
            cluster_domain=os.environ.get("CLUSTER_DOMAIN", "cluster.local"),
        )


def parse_logspath(logspath: str) -> Tuple[str, Dict[str, Any]]:
    """Classify a logspath: ("pvc", {name, subpath}) or ("cloud", {uri})."""
    if logspath.startswith("pvc://"):
        rest = logspath[len("pvc://"):]
        name, _, subpath = rest.partition("/")
        if not name:
            raise ValueError(f"bad logspath {logspath!r}: missing PVC name")
        return "pvc", {"name": name, "subpath": subpath}
    if not logspath:
        raise ValueError("empty logspath")
    return "cloud", {"uri": logspath}


class TensorboardReconciler(Reconciler):
    FOR = (TB_API, "Tensorboard")
    OWNS = [
        ("apps/v1", "Deployment"),
        ("v1", "Service"),
        ("networking.istio.io/v1beta1", "VirtualService"),
    ]

    def __init__(self, config: Optional[TensorboardConfig] = None):
        self.config = config or TensorboardConfig()

    def reconcile(self, client: Client, req: Request) -> Result:
        tb = client.get_opt(*self.FOR, req.name, req.namespace)
        if tb is None:
            return Result()
        try:
            dep = self._generate_deployment(client, tb)
        except (ValueError, KeyError, TypeError) as e:
            fresh = apimeta.deepcopy(tb)
            fresh["status"] = {
                "conditions": [
                    {"type": "Failed", "status": "True", "reason": "InvalidSpec", "message": str(e)}
                ]
            }
            client.update_status(fresh)
            return Result()
        rh.reconcile_object(client, dep, tb)
        rh.reconcile_object(client, self._generate_service(tb), tb)
        if self.config.use_istio:
            rh.reconcile_object(client, self._generate_virtual_service(tb), tb)
        self._update_status(client, tb)
        return Result()

    def _generate_deployment(self, client: Client, tb: Dict[str, Any]) -> Dict[str, Any]:
        name, ns = apimeta.name_of(tb), apimeta.namespace_of(tb)
        logspath = tb.get("spec", {}).get("logspath", "")
        kind, info = parse_logspath(logspath)

        volumes, mounts, env, logdir = [], [], [], logspath
        affinity: Dict[str, Any] = {}
        if kind == "pvc":
            volumes.append(
                {"name": "tb-logs", "persistentVolumeClaim": {"claimName": info["name"]}}
            )
            mounts.append({"name": "tb-logs", "mountPath": "/tb-logs", "subPath": info["subpath"] or None})
            mounts[-1] = {k: v for k, v in mounts[-1].items() if v is not None}
            logdir = "/tb-logs"
            if self.config.rwo_pvc_scheduling:
                node = self._rwo_pvc_node(client, ns, info["name"])
                if node:
                    affinity = {
                        "nodeAffinity": {
                            "requiredDuringSchedulingIgnoredDuringExecution": {
                                "nodeSelectorTerms": [
                                    {
                                        "matchExpressions": [
                                            {
                                                "key": "kubernetes.io/hostname",
                                                "operator": "In",
                                                "values": [node],
                                            }
                                        ]
                                    }
                                ]
                            }
                        }
                    }
        else:
            # Cloud path: mount GCP SA secret (reference :213-227).
            volumes.append({"name": "gcp-creds", "secret": {"secretName": "user-gcp-sa"}})
            mounts.append({"name": "gcp-creds", "mountPath": "/secret/gcp", "readOnly": True})
            env.append(
                {"name": "GOOGLE_APPLICATION_CREDENTIALS", "value": "/secret/gcp/user-gcp-sa.json"}
            )

        pod_spec: Dict[str, Any] = {
            "containers": [
                {
                    "name": "tensorboard",
                    "image": self.config.image,
                    "command": ["/usr/local/bin/tensorboard"],
                    "args": [
                        f"--logdir={logdir}",
                        "--bind_all",
                        "--port=6006",
                        # JAX/XLA profile plugin traces live under plugins/profile
                        # inside the logdir; no extra flags needed, listed here
                        # for operator discoverability.
                    ],
                    "ports": [{"containerPort": 6006, "name": "http"}],
                    "volumeMounts": mounts,
                    "env": env,
                }
            ],
            "volumes": volumes,
        }
        if affinity:
            pod_spec["affinity"] = affinity

        return apimeta.new_object(
            "apps/v1",
            "Deployment",
            name,
            ns,
            spec={
                "replicas": 1,
                "selector": {"matchLabels": {"app": "tensorboard", "tb-name": name}},
                "template": {
                    "metadata": {"labels": {"app": "tensorboard", "tb-name": name}},
                    "spec": pod_spec,
                },
            },
        )

    def _rwo_pvc_node(self, client: Client, ns: str, pvc_name: str) -> Optional[str]:
        """Node already mounting the RWO PVC (reference :437-447)."""
        pvc = client.get_opt("v1", "PersistentVolumeClaim", pvc_name, ns)
        if pvc is None:
            return None
        modes = pvc.get("spec", {}).get("accessModes") or []
        if "ReadWriteOnce" not in modes:
            return None
        for pod in client.list("v1", "Pod", ns):
            if pod.get("status", {}).get("phase") != "Running":
                continue
            for vol in pod.get("spec", {}).get("volumes", []) or []:
                claim = (vol.get("persistentVolumeClaim") or {}).get("claimName")
                if claim == pvc_name and pod.get("spec", {}).get("nodeName"):
                    return pod["spec"]["nodeName"]
        return None

    def _generate_service(self, tb: Dict[str, Any]) -> Dict[str, Any]:
        name, ns = apimeta.name_of(tb), apimeta.namespace_of(tb)
        return apimeta.new_object(
            "v1",
            "Service",
            name,
            ns,
            spec={
                "selector": {"app": "tensorboard", "tb-name": name},
                "ports": [{"name": f"http-{name}", "port": 80, "targetPort": 6006}],
            },
        )

    def _generate_virtual_service(self, tb: Dict[str, Any]) -> Dict[str, Any]:
        name, ns = apimeta.name_of(tb), apimeta.namespace_of(tb)
        prefix = f"/tensorboard/{ns}/{name}/"
        return apimeta.new_object(
            "networking.istio.io/v1beta1",
            "VirtualService",
            f"tensorboard-{ns}-{name}",
            ns,
            spec={
                "hosts": [self.config.istio_host],
                "gateways": [self.config.istio_gateway],
                "http": [
                    {
                        "match": [{"uri": {"prefix": prefix}}],
                        "rewrite": {"uri": "/"},
                        "route": [
                            {
                                "destination": {
                                    "host": f"{name}.{ns}.svc.{self.config.cluster_domain}",
                                    "port": {"number": 80},
                                }
                            }
                        ],
                    }
                ],
            },
        )

    def _update_status(self, client: Client, tb: Dict[str, Any]) -> None:
        name, ns = apimeta.name_of(tb), apimeta.namespace_of(tb)
        dep = client.get_opt("apps/v1", "Deployment", name, ns)
        conditions = (dep or {}).get("status", {}).get("conditions", [])
        ready = (dep or {}).get("status", {}).get("readyReplicas", 0)
        status = {"conditions": conditions, "readyReplicas": ready}
        if tb.get("status") != status:
            fresh = apimeta.deepcopy(tb)
            fresh["status"] = status
            client.update_status(fresh)

def main() -> None:  # python -m kubeflow_tpu.controllers.tensorboard
    from ..runtime.bootstrap import run_role

    run_role("tensorboard-controller", TensorboardReconciler(TensorboardConfig.from_env()))


if __name__ == "__main__":
    main()
