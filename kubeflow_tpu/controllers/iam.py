"""Cloud IAM plugin bodies for the profile controller.

The reference profile-controller binds each profile namespace's
``default-editor`` KSA to cloud identity two ways:

- **GCP workload identity** — adds a ``roles/iam.workloadIdentityUser``
  binding for ``serviceAccount:<project>.svc.id.goog[<ns>/<ksa>]`` on the
  target GCP service account's IAM policy
  (components/profile-controller/controllers/plugin_workload_identity.go:44-51,
  135-163).
- **AWS IRSA** — edits the IAM role's *assume-role trust policy* JSON so the
  OIDC federated statement's ``<issuer>:sub`` condition includes
  ``system:serviceaccount:<ns>:<ksa>``
  (plugin_iam.go:34-50, 131-244).

This module implements both as **pure policy-document transforms** (dict in →
dict out, no I/O) plus a ``CloudIamBackend`` that plugs into
``ProfileConfig.iam_backend`` and performs the cloud round-trip through
injectable transports. The default transports are stdlib-only: AWS calls are
SigV4-signed ``urllib`` requests (no boto3 in the image), GCP calls use a
bearer token from the environment or the GCE metadata server (no
google-auth). Tests exercise the transforms and the backend with fake
transports — no cloud calls, parity with the reference's
plugin_iam_test.go:1-303.

Deliberate fix over the reference: ``add_workload_identity_binding`` is
idempotent — the reference's ``addBinding`` appends a duplicate binding on
every reconcile (plugin_workload_identity.go:135-143).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import logging
import os
import re
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)

# Annotation keys (reference: plugin_workload_identity.go:33, plugin_iam.go:23).
GCP_ANNOTATION_KEY = "iam.gke.io/gcp-service-account"
AWS_ANNOTATION_KEY = "eks.amazonaws.com/role-arn"

GCP_SA_SUFFIX = ".iam.gserviceaccount.com"
WORKLOAD_IDENTITY_ROLE = "roles/iam.workloadIdentityUser"
AWS_TRUST_IDENTITY_SUBJECT = "system:serviceaccount:{ns}:{ksa}"
AWS_DEFAULT_AUDIENCE = "sts.amazonaws.com"

JsonDict = Dict[str, Any]


# =============================================================================
# GCP workload identity: policy transforms
# =============================================================================

def gcp_project_of(gcp_service_account: str) -> str:
    """``name@<project>.iam.gserviceaccount.com`` → ``<project>``.

    Reference: GetProjectID, plugin_workload_identity.go:53-64.
    """
    if not gcp_service_account.endswith(GCP_SA_SUFFIX):
        raise ValueError(f"{gcp_service_account!r} is not a valid GCP service account")
    m = re.search(r"@(.*?)\.", gcp_service_account)
    if m is None:
        raise ValueError(f"cannot extract project id from {gcp_service_account!r}")
    return m.group(1)


def workload_identity_member(project: str, namespace: str, ksa: str) -> str:
    """The workload-identity pool member string (plugin_workload_identity.go:123)."""
    return f"serviceAccount:{project}.svc.id.goog[{namespace}/{ksa}]"


def add_workload_identity_binding(policy: JsonDict, member: str) -> JsonDict:
    """Add ``member`` to the workloadIdentityUser binding. Idempotent."""
    out = json.loads(json.dumps(policy))  # deep copy, JSON-typed
    bindings: List[JsonDict] = out.setdefault("bindings", [])
    for b in bindings:
        if b.get("role") == WORKLOAD_IDENTITY_ROLE:
            members = b.setdefault("members", [])
            if member not in members:
                members.append(member)
            return out
    bindings.append({"role": WORKLOAD_IDENTITY_ROLE, "members": [member]})
    return out


def remove_workload_identity_binding(policy: JsonDict, member: str) -> JsonDict:
    """Remove ``member`` from every workloadIdentityUser binding; drop
    bindings that become empty (the reference leaves empty bindings behind —
    plugin_workload_identity.go:146-153 — which GCP rejects on set)."""
    out = json.loads(json.dumps(policy))
    kept: List[JsonDict] = []
    for b in out.get("bindings", []):
        if b.get("role") == WORKLOAD_IDENTITY_ROLE:
            b["members"] = [m for m in b.get("members", []) if m != member]
            if not b["members"]:
                continue
        kept.append(b)
    out["bindings"] = kept
    return out


# =============================================================================
# AWS IRSA: trust-policy transforms
# =============================================================================

def role_name_from_arn(arn: str) -> str:
    """``arn:aws:iam::<acct>:role/<name>`` → ``<name>`` (plugin_iam.go:250)."""
    return arn.rsplit("/", 1)[-1]


def issuer_from_provider_arn(arn: str) -> str:
    """``arn:aws:iam::<acct>:oidc-provider/<issuer>`` → ``<issuer>``
    (plugin_iam.go:246-248: everything after the FIRST slash)."""
    return arn.split("/", 1)[1] if "/" in arn else arn


def _federated_statement(doc: JsonDict) -> JsonDict:
    """The reference operates only on Statement[0] (plugin_iam.go:146-147)."""
    statements = doc.get("Statement") or []
    if not statements:
        raise ValueError("trust policy has no Statement")
    return statements[0]


def _sub_list(statement: JsonDict, key: str) -> List[str]:
    val = (statement.get("Condition") or {}).get("StringEquals", {}).get(key)
    if val is None:
        return []
    return [val] if isinstance(val, str) else list(val)


def add_trust_subject(doc: JsonDict, namespace: str, ksa: str) -> JsonDict:
    """Add ``system:serviceaccount:<ns>:<ksa>`` to statement 0's OIDC
    ``:sub`` condition. Returns the document unchanged if already present
    (the reference's ConditionExistError skip, plugin_iam.go:155-164).

    Deliberate fix over the reference: the transform edits statement 0
    in place instead of rebuilding the whole document
    (MakePolicyDocument, plugin_iam.go:253-270), which on a shared role
    would silently delete Statement[1:], non-StringEquals conditions, and
    any custom ``:aud`` values.
    """
    out = json.loads(json.dumps(doc))  # deep copy, JSON-typed
    statement = _federated_statement(out)
    provider_arn = (statement.get("Principal") or {}).get("Federated", "")
    issuer = issuer_from_provider_arn(provider_arn)
    subject = AWS_TRUST_IDENTITY_SUBJECT.format(ns=namespace, ksa=ksa)
    subjects = _sub_list(statement, f"{issuer}:sub")
    if subject in subjects:
        return out
    subjects.append(subject)
    equals = statement.setdefault("Condition", {}).setdefault("StringEquals", {})
    equals[f"{issuer}:sub"] = subjects
    # The reference pins the audience; only fill it when absent so a custom
    # audience on an existing role survives.
    equals.setdefault(f"{issuer}:aud", [AWS_DEFAULT_AUDIENCE])
    return out


def remove_trust_subject(doc: JsonDict, namespace: str, ksa: str) -> JsonDict:
    """Remove the namespace/ksa subject from statement 0; when the ``:sub``
    list becomes empty the key is dropped entirely (a bare ``null``/``[]``
    breaks IAM policy validation — plugin_iam.go:216-236). Everything else
    in the document is preserved."""
    out = json.loads(json.dumps(doc))
    statement = _federated_statement(out)
    provider_arn = (statement.get("Principal") or {}).get("Federated", "")
    issuer = issuer_from_provider_arn(provider_arn)
    subject = AWS_TRUST_IDENTITY_SUBJECT.format(ns=namespace, ksa=ksa)
    key = f"{issuer}:sub"
    subjects = [s for s in _sub_list(statement, key) if s != subject]
    equals = statement.setdefault("Condition", {}).setdefault("StringEquals", {})
    if subjects:
        equals[key] = subjects
    else:
        equals.pop(key, None)
    return out


# =============================================================================
# Stdlib transports (no boto3 / google-auth in the image)
# =============================================================================

def sigv4_headers(
    method: str,
    url: str,
    body: bytes,
    service: str,
    region: str,
    access_key: str,
    secret_key: str,
    session_token: Optional[str] = None,
    now: Optional[datetime.datetime] = None,
    extra_headers: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """AWS Signature Version 4 request signing, pure stdlib.

    Replaces the aws-sdk-go session the reference leans on
    (plugin_iam.go:70-76). Deterministic given ``now`` — unit-tested against
    the published AWS SigV4 example vector.
    """
    parsed = urllib.parse.urlsplit(url)
    host = parsed.netloc
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    canonical_uri = parsed.path or "/"
    # Canonical query: sorted by key, RFC3986-encoded.
    query_pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(query_pairs)
    )
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {"host": host, "x-amz-date": amz_date}
    for k, v in (extra_headers or {}).items():
        headers[k.lower()] = v.strip()
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed_headers = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join(
        [method, canonical_uri, canonical_query, canonical_headers, signed_headers, payload_hash]
    )

    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k_date = _hmac(f"AWS4{secret_key}".encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(), hashlib.sha256).hexdigest()

    out = {
        "X-Amz-Date": amz_date,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}"
        ),
    }
    if session_token:
        out["X-Amz-Security-Token"] = session_token
    return out


class AwsIamTransport:
    """GetRole / UpdateAssumeRolePolicy over the IAM query API with SigV4."""

    ENDPOINT = "https://iam.amazonaws.com/"

    def __init__(self, region: str = "us-east-1"):
        self.region = region

    def _call(self, params: Dict[str, str]) -> str:
        access_key = os.environ.get("AWS_ACCESS_KEY_ID")
        secret_key = os.environ.get("AWS_SECRET_ACCESS_KEY")
        if not access_key or not secret_key:
            raise RuntimeError(
                "AwsIamForServiceAccount plugin needs AWS_ACCESS_KEY_ID / "
                "AWS_SECRET_ACCESS_KEY in the controller environment"
            )
        body = urllib.parse.urlencode({**params, "Version": "2010-05-08"}).encode()
        content_type = "application/x-www-form-urlencoded; charset=utf-8"
        headers = sigv4_headers(
            "POST",
            self.ENDPOINT,
            body,
            service="iam",
            region=self.region,
            access_key=access_key,
            secret_key=secret_key,
            session_token=os.environ.get("AWS_SESSION_TOKEN"),
            extra_headers={"content-type": content_type},
        )
        headers["Content-Type"] = content_type
        req = urllib.request.Request(self.ENDPOINT, data=body, headers=headers, method="POST")
        with urllib.request.urlopen(req, timeout=30) as resp:  # noqa: S310
            return resp.read().decode()

    def get_trust_policy(self, role_name: str) -> JsonDict:
        xml = self._call({"Action": "GetRole", "RoleName": role_name})
        m = re.search(
            r"<AssumeRolePolicyDocument>(.*?)</AssumeRolePolicyDocument>", xml, re.S
        )
        if m is None:
            raise RuntimeError(f"GetRole({role_name}): no AssumeRolePolicyDocument in response")
        # The API returns the document URL-encoded (plugin_iam.go:86-89).
        return json.loads(urllib.parse.unquote(m.group(1)))

    def update_trust_policy(self, role_name: str, doc: JsonDict) -> None:
        self._call(
            {
                "Action": "UpdateAssumeRolePolicy",
                "RoleName": role_name,
                "PolicyDocument": json.dumps(doc),
            }
        )


class GcpIamTransport:
    """getIamPolicy / setIamPolicy on iam.googleapis.com with a bearer token
    from ``GOOGLE_OAUTH_ACCESS_TOKEN`` or the GCE metadata server."""

    ENDPOINT = "https://iam.googleapis.com/v1"
    METADATA_TOKEN_URL = (
        "http://metadata.google.internal/computeMetadata/v1/instance/service-accounts/default/token"
    )

    def _token(self) -> str:
        tok = os.environ.get("GOOGLE_OAUTH_ACCESS_TOKEN")
        if tok:
            return tok
        req = urllib.request.Request(
            self.METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
        )
        try:
            # Short timeout: on credential-less clusters this failing fast
            # keeps a misconfigured IAM plugin from stalling reconciles.
            with urllib.request.urlopen(req, timeout=2) as resp:  # noqa: S310
                return json.loads(resp.read())["access_token"]
        except (urllib.error.URLError, OSError, KeyError, ValueError) as e:
            raise RuntimeError(
                "WorkloadIdentity plugin needs GOOGLE_OAUTH_ACCESS_TOKEN or a "
                "reachable GCE metadata server"
            ) from e

    def _call(self, method: str, path: str, payload: Optional[JsonDict] = None) -> JsonDict:
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            f"{self.ENDPOINT}/{path}",
            data=data,
            headers={
                "Authorization": f"Bearer {self._token()}",
                "Content-Type": "application/json",
            },
            method=method,
        )
        with urllib.request.urlopen(req, timeout=30) as resp:  # noqa: S310
            return json.loads(resp.read() or b"{}")

    def get_policy(self, sa_resource: str) -> JsonDict:
        return self._call("POST", f"{sa_resource}:getIamPolicy")

    def set_policy(self, sa_resource: str, policy: JsonDict) -> None:
        self._call("POST", f"{sa_resource}:setIamPolicy", {"policy": policy})


# =============================================================================
# The backend ProfileConfig.iam_backend expects
# =============================================================================

class CloudIamBackend:
    """Callable ``(action, kind, spec, namespace)`` — the profile
    controller's cloud half of plugin apply/revoke. Transports are
    injectable; defaults are the stdlib implementations above."""

    KSA = "default-editor"  # reference: DEFAULT_EDITOR in both plugins

    def __init__(
        self,
        aws: Optional[AwsIamTransport] = None,
        gcp: Optional[GcpIamTransport] = None,
        ksa_project: Optional[str] = None,
    ):
        self.aws = aws or AwsIamTransport()
        self.gcp = gcp or GcpIamTransport()
        # The identity-pool project may differ from the GSA's project when
        # binding across projects (plugin_workload_identity.go:118-123).
        self.ksa_project = ksa_project or os.environ.get("WORKLOAD_IDENTITY_PROJECT")

    def __call__(self, action: str, kind: str, spec: JsonDict, namespace: str) -> None:
        if action not in ("apply", "revoke"):
            raise ValueError(f"unknown IAM action {action!r}")
        if kind == "WorkloadIdentity":
            self._gcp(action, spec.get("gcpServiceAccount", ""), namespace)
        elif kind == "AwsIamForServiceAccount":
            self._aws(action, spec.get("awsIamRole", ""), namespace)
        else:
            raise ValueError(f"unknown plugin kind {kind!r}")

    def _gcp(self, action: str, gcp_sa: str, namespace: str) -> None:
        project = gcp_project_of(gcp_sa)
        sa_resource = f"projects/{project}/serviceAccounts/{gcp_sa}"
        member = workload_identity_member(self.ksa_project or project, namespace, self.KSA)
        policy = self.gcp.get_policy(sa_resource)
        transform = (
            add_workload_identity_binding if action == "apply" else remove_workload_identity_binding
        )
        updated = transform(policy, member)
        if updated != policy:
            self.gcp.set_policy(sa_resource, updated)
        log.info("workload identity %s: %s on %s", action, member, gcp_sa)

    def _aws(self, action: str, role_arn: str, namespace: str) -> None:
        role_name = role_name_from_arn(role_arn)
        doc = self.aws.get_trust_policy(role_name)
        transform = add_trust_subject if action == "apply" else remove_trust_subject
        updated = transform(doc, namespace, self.KSA)
        if updated != doc:
            self.aws.update_trust_policy(role_name, updated)
        log.info("IRSA trust policy %s: ns=%s role=%s", action, namespace, role_name)
