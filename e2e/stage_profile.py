"""Per-stage ResNet-50 train-time decomposition (VERDICT r3 #1 follow-up).

The step-level profile (e2e/profile_step.py) attributes time to
fwd/bwd/optimizer but not to STAGES, and the isolated-kernel rates in
e2e/ceiling.py turned out to mispredict in-model cost (the 7x7 stem probe
measured 5.7 TF/s standalone, yet swapping in the 44-TF/s space-to-depth
stem moved the full step by <1% — XLA treats the conv differently in
context). This probe times each stage AS TRAINED: one fwd+bwd (wrt params
and input) over just that stage's blocks at its real activation shape,
BN in train mode, scanned inside one executable with the standard
anti-hoist carry perturbation and host-fetch barrier.

Output: ms and TF/s per stage + the sum vs the measured full step, i.e.
which stage is leaving MFU on the table and how much of the step the
stage model explains.

Run:  python -m e2e.stage_profile [--batch 256] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import time
from functools import partial
from typing import Any, Dict, List

import flax.linen as nn
import jax
import jax.numpy as jnp

try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


class StemTower(nn.Module):
    """conv7x7/2 (or s2d) + BN + ReLU + maxpool, exactly as ResNet runs it."""

    stem: str = "conv7x7"

    @nn.compact
    def __call__(self, x, train: bool = True):
        from kubeflow_tpu.models.resnet import space_to_depth

        conv = partial(nn.Conv, use_bias=False, dtype=jnp.bfloat16, param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                       epsilon=1e-5, dtype=jnp.bfloat16, param_dtype=jnp.float32)
        x = x.astype(jnp.bfloat16)
        if self.stem == "s2d":
            x = space_to_depth(x, 2)
            x = conv(64, (4, 4), (1, 1), padding=[(2, 1), (2, 1)], name="conv_init_s2d")(x)
        else:
            x = conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = nn.relu(norm(name="bn_init")(x))
        return nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))


class _ScaleBias(nn.Module):
    """BN stand-in: per-channel scale+bias with NO batch statistics — the
    'norm=frozen' variant that isolates what the stats reductions cost."""

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        return x * scale.astype(x.dtype) + bias.astype(x.dtype)


class StageTower(nn.Module):
    """One ResNet-50 bottleneck stage at its real shapes.

    ``norm_mode``: 'train' = real BN batch stats (what training runs);
    'eval' = running-average BN (no stats reduction); 'frozen' = scale+bias
    only (no reduction, no stats memory traffic).
    """

    filters: int
    blocks: int
    first_stride: int
    norm_mode: str = "train"

    @nn.compact
    def __call__(self, x, train: bool = True):
        from kubeflow_tpu.models.resnet import BottleneckBlock

        conv = partial(nn.Conv, use_bias=False, dtype=jnp.bfloat16, param_dtype=jnp.float32)
        if self.norm_mode == "frozen":
            def norm(name=None, scale_init=None):
                return _ScaleBias(name=name)
        else:
            norm = partial(nn.BatchNorm,
                           use_running_average=(self.norm_mode == "eval") or not train,
                           momentum=0.9, epsilon=1e-5, dtype=jnp.bfloat16,
                           param_dtype=jnp.float32)
        x = x.astype(jnp.bfloat16)
        for j in range(self.blocks):
            strides = (self.first_stride, self.first_stride) if j == 0 else (1, 1)
            x = BottleneckBlock(filters=self.filters, strides=strides, conv=conv,
                                norm=norm, act=nn.relu, name=f"block{j + 1}")(x)
        return x


def _flops_of(fn, *args) -> float:
    try:
        comp = jax.jit(fn).lower(*args).compile()
        fl = comp.cost_analysis()
        fl = fl[0] if isinstance(fl, (list, tuple)) else fl
        return float(fl.get("flops", 0.0))
    except Exception:
        return 0.0


def time_tower(module: nn.Module, x_shape, steps: int) -> Dict[str, Any]:
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, x_shape, jnp.float32)
    variables = module.init(rng, x)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})

    def fwd_bwd(params, batch_stats, x):
        def loss_fn(p, xx):
            out, updates = module.apply(
                {"params": p, "batch_stats": batch_stats}, xx, train=True,
                mutable=["batch_stats"])
            return jnp.sum(out.astype(jnp.float32)) * 1e-6, updates
        (loss, updates), grads = jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)(params, x)
        return loss, grads, updates

    @jax.jit
    def run(params, batch_stats, x):
        def body(c, _):
            xx = x + c * jnp.float32(1e-30)  # anti-hoist: body depends on carry
            loss, grads, _ = fwd_bwd(params, batch_stats, xx)
            gsum = sum(jnp.sum(g.astype(jnp.float32))
                       for g in jax.tree_util.tree_leaves(grads))
            return c + loss + gsum * jnp.float32(1e-30), ()
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=steps)
        return c

    def one_step(params, batch_stats, x):
        # return grads too — a loss-only analysis target lets XLA dead-code
        # the entire backward and undercounts FLOPs ~3x (round-4 bug)
        loss, grads, _ = fwd_bwd(params, batch_stats, x)
        gsum = sum(jnp.sum(g.astype(jnp.float32))
                   for g in jax.tree_util.tree_leaves(grads))
        return loss, gsum

    flops = _flops_of(one_step, params, batch_stats, x)
    out = run(params, batch_stats, x)
    float(out)  # compile + warm
    t0 = time.perf_counter()
    float(run(params, batch_stats, x))
    dt = (time.perf_counter() - t0) / steps
    return {"ms": dt * 1e3, "tflops": flops / dt / 1e12 if flops else None,
            "gflops": flops / 1e9}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--stem", default="conv7x7", choices=["conv7x7", "s2d"])
    ap.add_argument("--norm", default="train", choices=["train", "eval", "frozen"],
                    help="BN variant for the stage towers (isolates BN-stats cost)")
    ap.add_argument("--stage", action="append",
                    help="run only towers whose name contains this substring")
    args = ap.parse_args(argv)
    b = args.batch
    nm = args.norm

    towers = [
        ("stem", StemTower(stem=args.stem), (b, 224, 224, 3)),
        ("stage1 (3x bottleneck 64, 56x56)", StageTower(64, 3, 1, nm), (b, 56, 56, 64)),
        ("stage2 (4x bottleneck 128, 28x28)", StageTower(128, 4, 2, nm), (b, 56, 56, 256)),
        ("stage3 (6x bottleneck 256, 14x14)", StageTower(256, 6, 2, nm), (b, 28, 28, 512)),
        ("stage4 (3x bottleneck 512, 7x7)", StageTower(512, 3, 2, nm), (b, 14, 14, 1024)),
    ]
    if args.stage:
        towers = [t for t in towers if any(s in t[0] for s in args.stage)]
    rows: List[Dict[str, Any]] = []
    total_ms = 0.0
    for name, module, shape in towers:
        r = {"stage": name, **time_tower(module, shape, args.steps)}
        rows.append(r)
        total_ms += r["ms"]
        rate = f"{r['tflops']:.1f} TF/s" if r["tflops"] else "n/a"
        print(f"{name:38s} {r['ms']:8.2f} ms  {r['gflops']:9.1f} GF  {rate}", flush=True)
    print(f"{'sum of stages (fwd+bwd, no opt/head)':38s} {total_ms:8.2f} ms")
    print(json.dumps({"metric": "resnet_stage_profile", "batch": b,
                      "stem": args.stem, "rows": rows,
                      "sum_ms": round(total_ms, 2)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
