"""Straggler-plane e2e: detect, forensically dump, quarantine, reshard.

A live multichip (8-virtual-device) elastic run with per-worker step
beacons, chaos-degraded mid-flight, CI job straggler-e2e:

1. a clean REFERENCE run of the composed-4D GPT records the uninterrupted
   loss curve (the parity baseline);
2. the chaos run trains the same seeds as a 4-pod gang; the real trainer
   publishes a :class:`WorkerBeacon` from inside its step loop and three
   sibling worker threads heartbeat alongside it — a gang of four beacons
   federated through a real HTTP scrape into the MonitoringPlane's TSDB;
3. chaos injects ``slow_worker`` (x5 pacing) against one sibling — the
   StragglerDetector must flag it within the k-of-n window budget — then
   ``wedge_worker`` against another: the detector mints a hang verdict,
   the ``/debug/stacks`` ring captures an all-thread dump that names the
   wedged frame (``_wedge_wait``), the verdict attaches to the gang's
   federated bind trace, the hosting node is quarantined (ledger cordons
   it; the flight recorder explains follow-up misfits as ``quarantined``)
   and the gang drains;
4. ElasticTrainer reshards around the loss — the new gang lands only on
   un-cordoned nodes — and finishes with loss parity vs the reference.

``straggler_detect_seconds`` / ``hang_detect_seconds`` are printed as
metric lines for the STRAGGLER bench-gate family.

CPU-only; per-incarnation jit compiles dominate the ~minutes runtime.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )

import json
import shutil
import tempfile
import threading
import time
import traceback
import urllib.request
from typing import Optional

from e2e.junit import run_driver

NAMESPACE = "default"
TOTAL_STEPS = 60
CKPT_EVERY = 8
GRACE_SECONDS = 20.0
#: the gang: 4 single-worker pods x 2 chips over 3 nodes x 4 chips, so
#: quarantining any one node still leaves exactly enough for a reshard
SHAPE = {"pods": 4, "chips": 2, "pp": 4, "virtual": 1}
#: per-step pacing every beacon applies (the simulated collective) — the
#: skew baseline chaos stretches
STEP_PACING = 0.4
SKEW_FACTOR = 3.0
SLOW_FACTOR = 5.0
K, N = 3, 5
TICK_S = 0.25
HANG_DEADLINE = 4.0
#: detection budgets: k-of-n windows at the tick cadence (+ publish +
#: federation slack) for skew; the deadline itself + slack for hangs
STRAGGLER_BUDGET_S = N * TICK_S + 5.0
HANG_BUDGET_S = HANG_DEADLINE + 5.0
LOSS_PARITY_TOL = 1e-3


def _poll(fn, timeout: float = 30.0, interval: float = 0.05, desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _gang_pod(name, gang, size, chips, grace=None):
    from kubeflow_tpu.api.meta import new_object
    from kubeflow_tpu.scheduler.gang import (
        DRAIN_GRACE_ANNOTATION,
        POD_GROUP_LABEL,
        POD_GROUP_SIZE_ANNOTATION,
    )
    from kubeflow_tpu.tpu.topology import RESOURCE_TPU

    annotations = {POD_GROUP_SIZE_ANNOTATION: str(size)}
    if grace is not None:
        annotations[DRAIN_GRACE_ANNOTATION] = str(grace)
    return new_object(
        "v1", "Pod", name, NAMESPACE,
        labels={POD_GROUP_LABEL: gang},
        annotations=annotations,
        spec={
            "priorityClassName": "trial",
            "containers": [{
                "name": "trainer",
                "resources": {"limits": {RESOURCE_TPU: str(chips)}},
            }],
        },
    )


class SliceRequester:
    """Gang acquisition against the real scheduler; re-requests release the
    previous (drained) gang first, the way a job controller recreates its
    pod group."""

    def __init__(self, client, devices, prefix: str):
        self._client = client
        self._devices = list(devices)
        self._prefix = prefix
        self.gen = 0
        self.current_gang: Optional[str] = None
        self.current_pods: list = []

    def __call__(self, attempt: int):
        from kubeflow_tpu.training.elastic import SliceOffer

        for n in self.current_pods:
            self._client.delete_opt("v1", "Pod", n, NAMESPACE)
        self.gen += 1
        gang = f"{self._prefix}-g{self.gen}"
        names = [f"{gang}-{i}" for i in range(SHAPE["pods"])]
        for n in names:
            self._client.create(_gang_pod(
                n, gang, SHAPE["pods"], SHAPE["chips"], grace=GRACE_SECONDS))
        _poll(lambda: self._all_running(names), timeout=60.0,
              desc=f"gang {gang} running")
        self.current_gang = gang
        self.current_pods = names
        return SliceOffer(
            devices=self._devices[: SHAPE["pods"] * SHAPE["chips"]],
            pp=SHAPE["pp"], virtual_stages=SHAPE["virtual"],
            pods=names, namespace=NAMESPACE,
        )

    def _all_running(self, names) -> bool:
        pods = [self._client.get_opt("v1", "Pod", n, NAMESPACE) for n in names]
        return all(p is not None and (p.get("status") or {}).get("phase") == "Running"
                   for p in pods)

    def binding(self, names):
        return {n: ((self._client.get_opt("v1", "Pod", n, NAMESPACE) or {})
                    .get("spec") or {}).get("nodeName") for n in names}


def _sibling_loop(beacon, stop: threading.Event) -> None:
    """One simulated gang member: throttle (pacing + chaos interposition)
    then publish, forever — the same per-step cadence as the real trainer's
    beacon, without a model attached."""
    step = 0
    while not stop.is_set():
        t0 = time.perf_counter()
        wait = beacon.throttle()
        beacon.publish(
            {"total": time.perf_counter() - t0, "collective_wait": wait}, step)
        step += 1


def _http_json(port: int, path: str) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5.0) as resp:
        return json.loads(resp.read().decode("utf-8"))


def reference_run(client, devices) -> dict:
    """The uninterrupted baseline: same seeds, same shape, no chaos."""
    from kubeflow_tpu.parallel.composite import CompositeConfig
    from kubeflow_tpu.tpu.profiling import StepClock
    from kubeflow_tpu.training.checkpoint import Checkpointer
    from kubeflow_tpu.training.elastic import CompositeWorkload, ElasticTrainer

    ckpt_dir = tempfile.mkdtemp(prefix="straggler-ref-")
    requester = SliceRequester(client, devices, "ref")
    workload = CompositeWorkload(
        cfg=CompositeConfig(n_layers=8, vocab_size=64),
        num_micro=4, microbatch=4, clock=StepClock())
    trainer = ElasticTrainer(
        workload, Checkpointer(ckpt_dir, max_to_keep=2), requester,
        TOTAL_STEPS, checkpoint_every=CKPT_EVERY)
    try:
        report = trainer.run()
        assert report.completed, "reference run never finished"
        assert len(report.incarnations) == 1, report.incarnations
        return dict(report.losses)
    finally:
        for n in requester.current_pods:
            client.delete_opt("v1", "Pod", n, NAMESPACE)
        _poll(lambda: all(
            client.get_opt("v1", "Pod", n, NAMESPACE) is None
            for n in requester.current_pods), desc="reference gang released")
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def run(args) -> dict:
    import jax

    from kubeflow_tpu.api.meta import annotations_of
    from kubeflow_tpu.controllers.builtin import PodletReconciler, make_tpu_node
    from kubeflow_tpu.monitoring import (
        SCRAPE_ANNOTATION,
        SCRAPE_JOB_ANNOTATION,
        SCRAPE_URL_ANNOTATION,
        MonitoringPlane,
        StragglerDetector,
        TraceCollector,
        straggler_rules,
    )
    from kubeflow_tpu.monitoring.tsdb import TSDB
    from kubeflow_tpu.api.meta import new_object
    from kubeflow_tpu.parallel.composite import CompositeConfig
    from kubeflow_tpu.runtime.chaos import ChaosMonkey, ChaosSchedule, Fault
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.obs import mount_observability
    from kubeflow_tpu.runtime.tracing import (
        BIND_TRACEPARENT_ANNOTATION,
        parse_traceparent,
    )
    from kubeflow_tpu.scheduler import SchedulerReconciler
    from kubeflow_tpu.scheduler.gang import DRAIN_DEADLINE_ANNOTATION
    from kubeflow_tpu.services.dashboard import make_dashboard_app
    from kubeflow_tpu.tpu.profiling import StepClock
    from kubeflow_tpu.training.checkpoint import Checkpointer
    from kubeflow_tpu.training.elastic import (
        CompositeWorkload,
        ElasticTrainer,
        PreemptionHandler,
    )
    from kubeflow_tpu.training.heartbeat import WorkerBeacon, clear_beacons
    from kubeflow_tpu.web.auth import AuthConfig
    from kubeflow_tpu.web.http import App

    devices = jax.devices()
    assert len(devices) == 8, f"driver needs 8 virtual devices, got {len(devices)}"

    mgr = Manager()
    sched = SchedulerReconciler(
        assembly_timeout=5.0, reservation_ttl=5.0,
        backoff_base=0.05, backoff_cap=0.4)
    mgr.add(sched)
    mgr.add(PodletReconciler())
    client = mgr.client
    for i in range(3):
        client.create(make_tpu_node(f"tpu-node-{i}", "v5e", "2x2", 4))
    mgr.start()

    # -- phase A: the uninterrupted parity baseline ---------------------------
    ref_losses = reference_run(client, devices)

    # -- phase B: monitoring plane with the straggler detector ----------------
    clear_beacons()
    app = App("trainer")
    mount_observability(app)
    tsdb = TSDB()
    traces = TraceCollector(client=client)
    detector = StragglerDetector(
        tsdb, client=client, namespace=NAMESPACE,
        skew_factor=SKEW_FACTOR, k=K, n=N,
        hang_deadline_s=HANG_DEADLINE, default_grace_s=GRACE_SECONDS,
        traces=traces)
    plane = MonitoringPlane(
        client=client, tsdb=tsdb, stale_after=40, timeout_s=5.0,
        traces=traces, stragglers=detector)
    for rule in straggler_rules(step_slo_s=1.0):
        plane.rules.add(rule)
    plane.mount(app)
    httpd = app.serve(0)
    client.create(new_object(
        "v1", "Pod", "straggler-target", NAMESPACE,
        annotations={
            SCRAPE_ANNOTATION: "true",
            SCRAPE_URL_ANNOTATION: f"http://127.0.0.1:{httpd.port}/metrics",
            SCRAPE_JOB_ANNOTATION: "training",
        }))

    # -- phase B: the chaos run -----------------------------------------------
    ckpt_dir = tempfile.mkdtemp(prefix="straggler-e2e-")
    requester = SliceRequester(client, devices, "train")
    monkey = ChaosMonkey(client, ChaosSchedule([]), store=mgr.store)
    # the real trainer's beacon is worker 0 of the first gang; siblings
    # heartbeat as workers 1..3 (chaos targets land on siblings, so the
    # model keeps stepping while the gang degrades around it)
    real_worker = "train-g1-0"
    beacon = WorkerBeacon(real_worker, step_delay_s=STEP_PACING)
    workload = CompositeWorkload(
        cfg=CompositeConfig(n_layers=8, vocab_size=64),
        num_micro=4, microbatch=4, clock=StepClock(), beacon=beacon)
    trainer = ElasticTrainer(
        workload, Checkpointer(ckpt_dir, max_to_keep=2), requester,
        TOTAL_STEPS, checkpoint_every=CKPT_EVERY,
        handler_factory=lambda offer: PreemptionHandler(
            client, NAMESPACE, offer.pods, poll_interval=0.02))

    sibling_stop = threading.Event()
    sibling_threads: list = []
    chaos: dict = {}

    def orchestrate() -> None:
        try:
            _poll(lambda: requester.gen == 1 and requester.current_pods,
                  timeout=120.0, desc="first gang bound")
            pods = list(requester.current_pods)
            slow_w, wedge_w = pods[1], pods[2]
            for name in pods[1:]:
                b = WorkerBeacon(name, step_delay_s=STEP_PACING)
                t = threading.Thread(
                    target=_sibling_loop, args=(b, sibling_stop),
                    name=f"sibling-{name}", daemon=True)
                t.start()
                sibling_threads.append(t)
            # every gang member federated AND the real trainer is stepping
            # (past its incarnation-0 compile) before chaos begins
            _poll(lambda: (
                set(detector.snapshot()["workers"]) >= set(pods)
                and (detector.snapshot()["workers"][pods[0]]["stepIndex"]
                     or 0) >= 3),
                timeout=240.0, interval=0.1, desc="gang of 4 beacons stepping")

            t_slow = time.time()
            monkey.inject(Fault(0.0, "slow_worker", slow_w, param=SLOW_FACTOR))
            _poll(lambda: detector.snapshot()["workers"][slow_w]["flagged"],
                  timeout=30.0, interval=0.05, desc="slow worker flagged")
            chaos["straggler_detect_seconds"] = time.time() - t_slow
            chaos["slow_worker"] = slow_w

            wpod = client.get_opt("v1", "Pod", wedge_w, NAMESPACE)
            chaos["wedge_traceparent"] = annotations_of(wpod).get(
                BIND_TRACEPARENT_ANNOTATION)
            t_wedge = time.time()
            monkey.inject(Fault(0.0, "wedge_worker", wedge_w))
            verdict = _poll(
                lambda: (lambda v: v if v and v["worker"] == wedge_w else None)(
                    detector.snapshot()["lastHangVerdict"]),
                timeout=30.0, interval=0.05, desc="hang verdict")
            chaos["hang_detect_seconds"] = verdict["detectedAt"] - t_wedge
            chaos["verdict"] = dict(verdict)
            chaos["wedge_worker"] = wedge_w

            node = _poll(
                lambda: (detector.snapshot()["quarantined"] or [None])[0],
                timeout=15.0, desc="node quarantined")
            chaos["quarantined_node"] = node
            _poll(lambda: node in sched.ledger.snapshot()["cordoned"],
                  timeout=15.0, desc="ledger cordon")
            _poll(lambda: all(
                (p := client.get_opt("v1", "Pod", n, NAMESPACE)) is None
                or DRAIN_DEADLINE_ANNOTATION in annotations_of(p)
                for n in pods), timeout=15.0, desc="gang drain stamped")
            # one more scrape must land the hang counter in the TSDB (the
            # tick that minted the verdict scraped BEFORE detecting)
            _poll(lambda: any(
                lab.get("worker") == wedge_w for lab, _t, _v in
                tsdb.latest("training_hangs_detected_total")),
                timeout=10.0, desc="hang counter federated")
        except Exception:
            chaos["error"] = traceback.format_exc()
        finally:
            # detection is proven; stop the plane so the trainer's silent
            # re-compile in the next incarnation can't read as a hang
            plane.stop()
            monkey.stop()  # releases the wedge, restores the slow factor
            sibling_stop.set()

    plane.start(TICK_S)
    orch = threading.Thread(target=orchestrate, name="chaos-orchestrator",
                            daemon=True)
    orch.start()

    try:
        report = trainer.run()
        orch.join(timeout=60.0)
    finally:
        plane.stop()
        monkey.stop()
        sibling_stop.set()

    try:
        assert "error" not in chaos, f"chaos orchestration failed:\n{chaos['error']}"
        assert report.completed, f"training never finished: {report.incarnations}"

        # -- detection within the window budgets ------------------------------
        assert chaos["straggler_detect_seconds"] <= STRAGGLER_BUDGET_S, chaos
        assert chaos["hang_detect_seconds"] <= HANG_BUDGET_S, chaos
        assert chaos["verdict"]["kind"] == "hang"
        assert chaos["verdict"]["worker"] == chaos["wedge_worker"]

        # -- forensics: the stack ring names the wedged frame -----------------
        assert "_wedge_wait" in chaos["verdict"]["stackThreads"], chaos["verdict"]
        stacks = _http_json(httpd.port, "/debug/stacks?capture=0")
        hang_dumps = [d for d in stacks["history"]
                      if d["reason"] == f"hang:{chaos['wedge_worker']}"]
        assert hang_dumps, [d["reason"] for d in stacks["history"]]
        wedged_threads = [
            t for t in hang_dumps[-1]["threads"]
            if any(f["function"] == "_wedge_wait" for f in t["frames"])]
        assert wedged_threads, "stack dump does not name the wedged frame"

        # -- the verdict rode the gang's federated bind trace -----------------
        tp = parse_traceparent(chaos["wedge_traceparent"] or "")
        assert tp is not None, "scheduler never stamped a bind traceparent"
        federated = traces.trace(tp[0])
        assert federated is not None, "bind trace never federated"
        assert any(v["kind"] == "hang" for v in federated.get("verdicts", [])), \
            federated.get("verdicts")

        # -- quarantine → cordon → reshard around the loss --------------------
        bad_node = chaos["quarantined_node"]
        assert report.preemptions_survived >= 1, report.incarnations
        assert len(report.incarnations) == 2, report.incarnations
        assert report.incarnations[0]["outcome"] == "preempted"
        placement = requester.binding(requester.current_pods)
        assert all(n and n != bad_node for n in placement.values()), (
            f"reshard landed on quarantined node {bad_node}: {placement}")
        verdict_reasons = {
            v["node"]: v["reason"]
            for v in sched.ledger.explain(
                (NAMESPACE, "probe"), [(4, {})], now=time.time())}
        assert verdict_reasons.get(bad_node) == "quarantined", verdict_reasons

        # the flight recorder explains a follow-up misfit as `quarantined`:
        # with the reshard holding 8 of the 12 chips, a 4-chip probe only
        # fits on the cordoned node
        client.create(_gang_pod("probe-0", "probe", 1, 4))
        decision = _poll(
            lambda: sched.flight.last_for(f"{NAMESPACE}/probe"),
            timeout=20.0, desc="probe flight record")
        probe_reasons = {n.get("node"): n.get("reason")
                         for n in decision.nodes}
        assert probe_reasons.get(bad_node) == "quarantined", probe_reasons
        client.delete_opt("v1", "Pod", "probe-0", NAMESPACE)

        # -- loss parity vs the uninterrupted reference -----------------------
        final = TOTAL_STEPS - 1
        delta = abs(report.losses[final] - ref_losses[final])
        assert delta <= LOSS_PARITY_TOL * max(1.0, abs(ref_losses[final])), (
            f"loss parity broken: chaos {report.losses[final]:.6f} vs "
            f"reference {ref_losses[final]:.6f}")
        max_step_delta = max(
            abs(report.losses[s] - ref_losses[s]) for s in ref_losses)

        # -- events + fault accounting ----------------------------------------
        reasons = {e["reason"] for e in client.list("v1", "Event", NAMESPACE)}
        assert {"WorkerStraggling", "WorkerHung", "NodeQuarantined"} <= reasons, \
            reasons
        fired = sorted(f.kind for f in monkey.fired)
        assert fired == ["slow_worker", "wedge_worker"], fired

        # -- federation: beacons + scores in the TSDB, dashboard section ------
        federated_workers = {lab.get("worker") for lab, _t, _v in
                             tsdb.latest("training_worker_step_wall_seconds")}
        assert len(federated_workers) >= 4, federated_workers
        scores = {lab.get("worker"): v for lab, _t, v in
                  tsdb.latest("training_straggler_score")}
        assert scores.get(chaos["slow_worker"], 0.0) >= K / N, scores
        beacon_view = _http_json(httpd.port, "/debug/beacon")
        assert chaos["slow_worker"] in beacon_view["workers"]

        dash = make_dashboard_app(client, auth=AuthConfig(disable_auth=True),
                                  monitoring=plane)
        overview = dash.call("GET", "/api/metrics/platform?window=120",
                             None, {"kubeflow-userid": "ops@example.com"})
        assert overview.status == 200, overview.body
        sect = overview.body["stragglers"]
        assert sect is not None, "dashboard stragglers section missing"
        assert sect["workerScores"].get(chaos["slow_worker"], 0.0) >= K / N
        assert bad_node in sect["activeQuarantines"], sect
        assert sect["lastHangVerdict"]["worker"] == chaos["wedge_worker"]
        assert sect["hangsDetected"].get(chaos["wedge_worker"]) == 1, sect

        summary = {
            "ok": True,
            "straggler_detect_seconds": round(
                chaos["straggler_detect_seconds"], 3),
            "hang_detect_seconds": round(chaos["hang_detect_seconds"], 3),
            "quarantined_node": bad_node,
            "incarnations": [
                {k: v for k, v in i.items() if k != "offer"}
                for i in report.incarnations
            ],
            "final_loss": round(report.losses[final], 6),
            "reference_final_loss": round(ref_losses[final], 6),
            "max_step_loss_delta": round(max_step_delta, 8),
            "stack_threads": chaos["verdict"]["stackThreads"],
        }
        # metric lines for the STRAGGLER_r* bench-gate family
        print(json.dumps({"metric": "straggler_detect_seconds",
                          "value": round(chaos["straggler_detect_seconds"], 3)}))
        print(json.dumps({"metric": "hang_detect_seconds",
                          "value": round(chaos["hang_detect_seconds"], 3)}))
        print(json.dumps(summary))
        return summary
    finally:
        for t in sibling_threads:
            t.join(timeout=5.0)
        mgr.stop()
        httpd.close()
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        clear_beacons()


def main(argv=None) -> int:
    return run_driver(
        suite_name="straggler-e2e",
        class_name="StragglerPlaneDryrun",
        case_name="slow-and-wedged-worker-quarantine-reshard",
        make_case=lambda args: lambda: run(args),
        argv=argv,
    )


if __name__ == "__main__":
    raise SystemExit(main())
