"""End-to-end test harness — the analog of the reference's ``testing/`` tree.

The reference drives e2e against a live CI cluster (SURVEY.md §4 tier 4):
Katib StudyJob runs (testing/katib_studyjob_test.py), TF Serving predict
checks (testing/test_tf_serving.py), a Selenium spawner flow
(testing/test_jwa.py), with deploy/wait/retry utilities and junit XML
results shipped to gubernator (test_tf_serving.py:139-143).

Here the "cluster" is the in-process platform (kubeflow_tpu.platform) plus
fake TPU nodes, so the same flows run hermetically on CPU; against a real
deployment the drivers work unchanged by pointing their base URLs at live
services. Each driver module has a ``main()`` and writes junit XML.
"""

from .cluster import E2ECluster, unique_namespace, wait_for_condition
from .junit import TestCaseResult, write_junit
from .retry import run_with_retry

__all__ = [
    "E2ECluster",
    "TestCaseResult",
    "run_with_retry",
    "unique_namespace",
    "wait_for_condition",
    "write_junit",
]
