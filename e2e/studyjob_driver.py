"""StudyJob e2e driver — the analog of testing/katib_studyjob_test.py.

The reference creates a StudyJob via ksonnet and polls until
``status.condition in ["Running"]`` under a 10-minute deadline
(katib_studyjob_test.py:128-193, :205-206). This driver goes further, the
way a Katib user actually judges a study: wait for Running, then for
Completed, and assert the optimal trial improved on the worst trial.

Run standalone:  python -m e2e.studyjob_driver [--objective quadratic|mnist]
Writes junit XML (test_tf_serving.py:139-143 pattern).
"""

from __future__ import annotations

import sys
from typing import Any, Dict

from kubeflow_tpu.controllers.studyjob import STUDY_API, InProcessTrialRunner
from kubeflow_tpu.hpo.trials import mnist_objective, quadratic_objective

from .cluster import E2ECluster, unique_namespace, wait_for_condition
from .junit import run_driver

OBJECTIVES = {"quadratic": quadratic_objective, "mnist": mnist_objective}


def studyjob_cr(name: str, ns: str, max_trials: int, parallel: int,
                early_stopping: bool = False) -> Dict[str, Any]:
    spec_extra: Dict[str, Any] = {}
    if early_stopping:
        spec_extra["earlyStopping"] = {
            "algorithmName": "medianstop", "settings": {"minTrials": 3}}
    return {
        "apiVersion": STUDY_API,
        "kind": "StudyJob",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "objective": {"type": "maximize", "objectiveMetricName": "accuracy"},
            "algorithm": {"algorithmName": "bayesian"},
            "parallelTrialCount": parallel,
            "maxTrialCount": max_trials,
            **spec_extra,
            "parameters": [
                {
                    "name": "lr",
                    "parameterType": "double",
                    "feasibleSpace": {"min": "1e-4", "max": "1.0", "logScale": True},
                },
                {
                    "name": "width",
                    "parameterType": "int",
                    "feasibleSpace": {"min": "8", "max": "64"},
                },
            ],
            "trialTemplate": {"image": "kubeflow-tpu/trial-jax:latest"},
        },
    }


def run_studyjob_e2e(
    objective: str = "quadratic",
    max_trials: int = 6,
    parallel: int = 2,
    timeout: float = 120.0,
    early_stopping: bool = False,
) -> Dict[str, Any]:
    """Create a StudyJob, drive it to completion, return its final status
    (including measured trials/hour — the BASELINE Katib metric).
    ``early_stopping`` turns on the median-stopping rule: bad trials get
    pruned mid-run (hpo/earlystop.py), raising trials/hour at equal
    best-trial quality."""
    import time as _time

    with E2ECluster(trial_runner=InProcessTrialRunner(OBJECTIVES[objective])) as cluster:
        ns = cluster.create_profile("katib-e2e@example.com", unique_namespace("katib"))
        t_start = _time.perf_counter()
        cluster.client.create(
            studyjob_cr("study-e2e", ns, max_trials, parallel, early_stopping))

        def get_phase() -> str:
            study = cluster.client.get(STUDY_API, "StudyJob", "study-e2e", ns)
            return (study.get("status") or {}).get("phase", "")

        # The reference's pass condition: the study reaches Running in time.
        wait_for_condition(
            lambda: get_phase() in ("Running", "Completed"),
            timeout=timeout,
            desc="studyjob Running",
        )
        wait_for_condition(
            lambda: get_phase() == "Completed", timeout=timeout, desc="studyjob Completed"
        )

        study = cluster.client.get(STUDY_API, "StudyJob", "study-e2e", ns)
        status = study["status"]
        finished = status["trialsSucceeded"] + status.get("trialsPruned", 0)
        assert finished == max_trials, status
        optimal = status.get("currentOptimalTrial")
        assert optimal, "completed study published no optimal trial"
        best = optimal["observation"]["accuracy"]

        trials = cluster.client.list(STUDY_API, "Trial", ns)
        assert len(trials) == max_trials, f"expected {max_trials} trials, got {len(trials)}"
        observed = [
            (t.get("status", {}).get("metrics") or {}).get("accuracy") for t in trials
        ]
        observed = [v for v in observed if v is not None]
        assert abs(best - max(observed)) < 1e-9, (best, max(observed))
        elapsed = _time.perf_counter() - t_start
        status["elapsedSeconds"] = round(elapsed, 3)
        status["trialsPerHour"] = round(max_trials / elapsed * 3600.0, 1)
        return status


def main(argv=None) -> int:
    def add_args(parser):
        parser.add_argument("--objective", choices=sorted(OBJECTIVES), default="quadratic")
        parser.add_argument("--max-trials", type=int, default=6)
        parser.add_argument("--timeout", type=float, default=120.0)
        parser.add_argument("--early-stopping", action="store_true",
                            help="enable the median-stopping pruner")

    return run_driver(
        "e2e-studyjob",
        "StudyJobE2E",
        lambda args: f"studyjob-{args.objective}",
        lambda args: lambda: run_studyjob_e2e(
            args.objective, args.max_trials, timeout=args.timeout,
            early_stopping=args.early_stopping),
        argv=argv,
        add_args=add_args,
        default_junit="junit_studyjob.xml",
    )


if __name__ == "__main__":
    sys.exit(main())
