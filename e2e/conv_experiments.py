"""Conv-ceiling attack experiments (VERDICT r3 #1).

BASELINE.md's ceiling analysis claims ResNet's 64-channel convs are bound by
the op MIX (a 128-wide MXU half-idle below 128 contraction/output channels),
not by the framework. That claim was measured only via
``jax.lax.conv_general_dilated`` — i.e. via XLA's chosen formulation. These
probes attack the bound directly by measuring the SAME arithmetic in every
formulation a custom kernel could choose, using the honest harness from
e2e/ceiling.py (all iterations inside one ``lax.scan`` executable, chained
bodies, host-fetch barrier — see BASELINE.md "integrity notes").

Stage-1 conv3x3 (batch 256, 56x56, 64->64, bf16) as a GEMM is
[M=256*56*56=802816, K=9*64=576] @ [K, N=64]:

1. ``gemm_conv_style``   — [M, 576] @ [576, 64]: XLA-conv-like orientation,
   output channels (64) in the minor/lane dim -> half the MXU lanes idle.
2. ``gemm_spatial_lanes``— [64, 576] @ [576, M]: the transposed orientation a
   Pallas kernel can pick — spatial in lanes (full width), c_out streamed as
   rows. Same FLOPs.
3. ``gemm_tap_dots``     — 9 x ([64, 64] @ [64, M]): the no-im2col variant
   (one dot per 3x3 tap); contraction depth 64 halves MXU depth utilization.
4. ``conv_xla``          — the actual ``conv_general_dilated`` at the stage
   shape (control; BASELINE.md row says 61.4 TF/s).
5. ``conv_xla_fused``    — conv + BN-apply + ReLU, measuring whether the
   epilogue is free (XLA fusion) or a separate HBM pass.
6. ``conv_stem`` / ``conv_stem_s2d`` — the 7x7/2 stem on 224x224x3 vs the
   space-to-depth repack (112x112x12, 4x4/1 kernel = identical arithmetic,
   4x the input channels feeding the MXU).

Run:  python -m e2e.conv_experiments [--probe NAME]
Prints one line per probe + a JSON summary. Results recorded in BASELINE.md.
"""

from __future__ import annotations

import argparse
import json
import os

from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp

_CACHE = os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cache")
try:
    jax.config.update("jax_compilation_cache_dir", _CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

# Harness shared with the ceiling probe so rates stay comparable under the
# same CEILING_CHAIN knob (one copy of the scan/amortization rationale).
from e2e.ceiling import CHAIN, _timed  # noqa: E402

ITERS = int(os.environ.get("CEILING_ITERS", "20"))

# Stage-1 conv3x3 as GEMM
B, HW, C = 256, 56, 64
M = B * HW * HW          # 802816
K = 9 * C                # 576


def _gemm_probe(m: int, k: int, n: int, name: str) -> Dict[str, Any]:
    """y <- (x @ w) folded back into x's shape via a cheap projection, chained
    so every dot stays live. x is a jit ARGUMENT (closure capture would be
    serialized into the remote-compile request on this backend)."""
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), jnp.bfloat16) * 0.05
    w = jax.random.normal(key, (k, n), jnp.bfloat16) * 0.05
    proj = jax.random.normal(key, (n, k), jnp.bfloat16) * 0.05

    @jax.jit
    def run(x, w, proj):
        def body(x, _):
            for _i in range(CHAIN):
                y = jax.lax.dot(x, w)            # [m, n]
                x = jnp.abs(jax.lax.dot(y, proj)) * 0.05  # back to [m, k], non-linear
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=ITERS)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x, w, proj), ITERS * CHAIN)
    flops = 2.0 * m * k * n * 2  # two dots per chain step
    return {"kernel": name, "tflops": flops / dt / 1e12, "iter_s": dt}


def gemm_conv_style() -> Dict[str, Any]:
    return _gemm_probe(M, K, C, f"gemm[{M}x{K}]@[{K}x{C}] (cout in lanes)")


def gemm_spatial_lanes() -> Dict[str, Any]:
    return _gemm_probe(C, K, M, f"gemm[{C}x{K}]@[{K}x{M}] (spatial in lanes)")


def gemm_tap_dots() -> Dict[str, Any]:
    """9 tap-dots of K=64: w9[9,64,64] x x[64,M] -> summed [64,M]."""
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (C, M), jnp.bfloat16) * 0.05
    w9 = jax.random.normal(key, (9, C, C), jnp.bfloat16) * 0.05

    @jax.jit
    def run(x, w9):
        def body(x, _):
            for _i in range(CHAIN):
                y = jnp.zeros((C, M), jnp.float32)
                for t in range(9):
                    y = y + jax.lax.dot(w9[t].T, x, preferred_element_type=jnp.float32)
                x = jnp.abs(y).astype(jnp.bfloat16) * 0.05
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=ITERS)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x, w9), ITERS * CHAIN)
    flops = 2.0 * C * C * M * 9
    return {"kernel": "9 tap-dots [64x64]@[64xM] (K=64)", "tflops": flops / dt / 1e12, "iter_s": dt}


def _conv_probe(batch: int, hw: int, cin: int, cout: int, ksz: int, stride: int,
                name: str, fuse_bn_relu: bool = False) -> Dict[str, Any]:
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (batch, hw, hw, cin), jnp.bfloat16)
    k = jax.random.normal(key, (ksz, ksz, cin, cout), jnp.bfloat16) * 0.05
    ohw = hw // stride
    proj = jax.random.normal(key, (1, 1, cout, cin), jnp.bfloat16) * 0.05
    scale = jax.random.normal(key, (cout,), jnp.bfloat16) * 0.1
    bias = jax.random.normal(key, (cout,), jnp.bfloat16) * 0.1
    dn = jax.lax.conv_dimension_numbers(x0.shape, k.shape, ("NHWC", "HWIO", "NHWC"))
    dn_proj = jax.lax.conv_dimension_numbers((batch, ohw, ohw, cout), proj.shape,
                                             ("NHWC", "HWIO", "NHWC"))

    @jax.jit
    def run(x, k, proj, scale, bias):
        def body(x, _):
            for _i in range(CHAIN):
                y = jax.lax.conv_general_dilated(x, k, (stride, stride), "SAME",
                                                 dimension_numbers=dn)
                if fuse_bn_relu:
                    y = jnp.maximum(y * scale + bias, 0.0)
                z = jax.lax.conv_general_dilated(y, proj, (1, 1), "SAME",
                                                 dimension_numbers=dn_proj) * (1.0 / hw)
                if stride != 1:
                    z = jnp.repeat(jnp.repeat(z, stride, 1), stride, 2)  # back to hw
                x = jnp.abs(z).astype(jnp.bfloat16)
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=ITERS)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x0, k, proj, scale, bias), ITERS * CHAIN)
    flops = 2.0 * batch * ohw * ohw * (ksz * ksz * cin * cout + cout * cin)
    return {"kernel": name, "tflops": flops / dt / 1e12, "iter_s": dt}


def conv_xla() -> Dict[str, Any]:
    return _conv_probe(B, HW, C, C, 3, 1, f"conv3x3 b{B} {HW}x{HW}x{C}->{C} (XLA)")


def conv_xla_fused() -> Dict[str, Any]:
    return _conv_probe(B, HW, C, C, 3, 1,
                       f"conv3x3+bn+relu b{B} {HW}x{HW}x{C}->{C} (XLA)", fuse_bn_relu=True)


def conv_stem() -> Dict[str, Any]:
    # 7x7/2 on 224x224x3: K = 49*3 = 147 contraction, 3 input channels of a
    # 128-lane load -> the classic worst case.
    return _conv_probe(B, 224, 3, 64, 7, 2, f"stem conv7x7/2 b{B} 224x224x3->64 (XLA)")


def conv_stem_s2d() -> Dict[str, Any]:
    # Space-to-depth: x[224,224,3] -> [112,112,12] (2x2 blocks into channels);
    # the 7x7/2 conv becomes a 4x4/1 conv on the repacked grid (the 7x7
    # kernel zero-padded to 8x8 and regrouped — MLPerf-style stem packing).
    # 16*12=192 taps vs 147: 31% more nominal FLOPs, but 4x the input
    # channels feeding the MXU. Compare iter_s against conv_stem — both
    # compute the full stem from the same input information.
    return _conv_probe(B, 112, 12, 64, 4, 1, f"stem-s2d conv4x4 b{B} 112x112x12->64 (XLA)")


def _conv_bwd_probe(which: str, cin: int = C, hw: int = HW) -> Dict[str, Any]:
    """Backward-pass decomposition at the stage-1 3x3 shape: time
    fwd+grad-wrt-x ('x'), fwd+grad-wrt-w ('w'), or the full training shape
    ('both'). The loss is sum(abs(conv)) so dY depends on x (a plain sum
    would make dY constant-foldable); grads feed the next chain step so
    nothing is dead."""
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (B, hw, hw, cin), jnp.bfloat16)
    k0 = jax.random.normal(key, (3, 3, cin, cin), jnp.bfloat16) * 0.05
    dn = jax.lax.conv_dimension_numbers(x0.shape, k0.shape, ("NHWC", "HWIO", "NHWC"))

    def loss(x, k):
        y = jax.lax.conv_general_dilated(x, k, (1, 1), "SAME", dimension_numbers=dn)
        return jnp.sum(jnp.abs(y.astype(jnp.float32)))

    @jax.jit
    def run(x, k):
        def body(x, _):
            for _i in range(CHAIN):
                if which == "x":
                    dx = jax.grad(loss, argnums=0)(x, k)
                    x = (jnp.abs(dx) * 0.01).astype(jnp.bfloat16)
                elif which == "w":
                    dw = jax.grad(loss, argnums=1)(x, k)
                    # dw is tiny [3,3,cin,cin]; keep it live through x
                    x = x * (1.0 + jnp.sum(jnp.abs(dw)) * jnp.bfloat16(1e-30))
                else:
                    dx, dw = jax.grad(loss, argnums=(0, 1))(x, k)
                    x = (jnp.abs(dx) * 0.01
                         + jnp.sum(jnp.abs(dw)) * jnp.bfloat16(1e-30)).astype(jnp.bfloat16)
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=ITERS)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x0, k0), ITERS * CHAIN)
    conv_f = 2.0 * B * hw * hw * 9 * cin * cin
    flops = conv_f * (3.0 if which == "both" else 2.0)  # fwd + 1-2 grad convs
    return {"kernel": f"conv3x3 {hw}x{hw}x{cin} fwd+grad_{which}",
            "tflops": flops / dt / 1e12, "iter_s": dt}


def _conv1x1_bwd_probe(cin: int, cout: int, hw: int = HW) -> Dict[str, Any]:
    """fwd+bwd of the bottleneck's 1x1 convs (projection GEMMs)."""
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (B, hw, hw, cin), jnp.bfloat16)
    k0 = jax.random.normal(key, (1, 1, cin, cout), jnp.bfloat16) * 0.05
    dn = jax.lax.conv_dimension_numbers(x0.shape, k0.shape, ("NHWC", "HWIO", "NHWC"))

    def loss(x, k):
        y = jax.lax.conv_general_dilated(x, k, (1, 1), "SAME", dimension_numbers=dn)
        return jnp.sum(jnp.abs(y.astype(jnp.float32)))

    @jax.jit
    def run(x, k):
        def body(x, _):
            for _i in range(CHAIN):
                dx, dw = jax.grad(loss, argnums=(0, 1))(x, k)
                x = (jnp.abs(dx) * 0.01
                     + jnp.sum(jnp.abs(dw)) * jnp.bfloat16(1e-30)).astype(jnp.bfloat16)
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=ITERS)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x0, k0), ITERS * CHAIN)
    flops = 3.0 * 2.0 * B * hw * hw * cin * cout
    return {"kernel": f"conv1x1 {hw}x{hw} {cin}->{cout} fwd+grad_both",
            "tflops": flops / dt / 1e12, "iter_s": dt}


def conv1x1_grad_reduce() -> Dict[str, Any]:
    return _conv1x1_bwd_probe(256, 64)


def conv1x1_grad_expand() -> Dict[str, Any]:
    return _conv1x1_bwd_probe(64, 256)


def bottleneck_block_fwd_bwd() -> Dict[str, Any]:
    """The WHOLE stage-1 bottleneck (1x1 256->64, 3x3 64->64, 1x1 64->256 +
    relu + residual; frozen scale/bias norm) fwd+bwd — isolates whether the
    stage tower's deficit is the conv mix itself or the BN/elementwise
    interleave around it."""
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (B, HW, HW, 256), jnp.bfloat16) * 0.1
    ks = {
        "k1": jax.random.normal(key, (1, 1, 256, 64), jnp.bfloat16) * 0.05,
        "k2": jax.random.normal(key, (3, 3, 64, 64), jnp.bfloat16) * 0.05,
        "k3": jax.random.normal(key, (1, 1, 64, 256), jnp.bfloat16) * 0.05,
        "s1": jnp.ones((64,), jnp.bfloat16), "b1": jnp.zeros((64,), jnp.bfloat16),
        "s2": jnp.ones((64,), jnp.bfloat16), "b2": jnp.zeros((64,), jnp.bfloat16),
        "s3": jnp.ones((256,), jnp.bfloat16), "b3": jnp.zeros((256,), jnp.bfloat16),
    }

    def block(x, p):
        def conv(x, k):
            dn = jax.lax.conv_dimension_numbers(x.shape, k.shape, ("NHWC", "HWIO", "NHWC"))
            return jax.lax.conv_general_dilated(x, k, (1, 1), "SAME", dimension_numbers=dn)
        y = jnp.maximum(conv(x, p["k1"]) * p["s1"] + p["b1"], 0)
        y = jnp.maximum(conv(y, p["k2"]) * p["s2"] + p["b2"], 0)
        y = conv(y, p["k3"]) * p["s3"] + p["b3"]
        return jnp.maximum(x + y, 0)

    def loss(x, p):
        return jnp.sum(jnp.abs(block(x, p).astype(jnp.float32)))

    @jax.jit
    def run(x, p):
        def body(x, _):
            for _i in range(CHAIN):
                dx, dp = jax.grad(loss, argnums=(0, 1))(x, p)
                dpsum = sum(jnp.sum(jnp.abs(g)) for g in jax.tree_util.tree_leaves(dp))
                x = (jnp.abs(dx) * 0.05 + dpsum * jnp.bfloat16(1e-30)).astype(jnp.bfloat16)
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=ITERS)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x0, ks), ITERS * CHAIN)
    conv_f = 2.0 * B * HW * HW * (256 * 64 + 9 * 64 * 64 + 64 * 256)
    flops = 3.0 * conv_f  # fwd + dX + dW
    return {"kernel": "bottleneck(256->64->64->256) fwd+bwd frozen-norm",
            "tflops": flops / dt / 1e12, "iter_s": dt}


def conv_grad_x() -> Dict[str, Any]:
    return _conv_bwd_probe("x")


def conv_grad_w() -> Dict[str, Any]:
    return _conv_bwd_probe("w")


def conv_grad_both() -> Dict[str, Any]:
    return _conv_bwd_probe("both")


def conv_grad_both_128() -> Dict[str, Any]:
    return _conv_bwd_probe("both", cin=128, hw=28)


PROBES: Dict[str, Callable[[], Dict[str, Any]]] = {
    "gemm_conv_style": gemm_conv_style,
    "gemm_spatial_lanes": gemm_spatial_lanes,
    "gemm_tap_dots": gemm_tap_dots,
    "conv_xla": conv_xla,
    "conv_xla_fused": conv_xla_fused,
    "conv_stem": conv_stem,
    "conv_stem_s2d": conv_stem_s2d,
    "conv_grad_x": conv_grad_x,
    "conv_grad_w": conv_grad_w,
    "conv_grad_both": conv_grad_both,
    "conv_grad_both_128": conv_grad_both_128,
    "conv1x1_grad_reduce": conv1x1_grad_reduce,
    "conv1x1_grad_expand": conv1x1_grad_expand,
    "bottleneck_block_fwd_bwd": bottleneck_block_fwd_bwd,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--probe", choices=sorted(PROBES), action="append",
                    help="run only these probes (default: all)")
    args = ap.parse_args(argv)
    names = args.probe or list(PROBES)
    rows: List[Dict[str, Any]] = []
    for name in names:
        try:
            r = PROBES[name]()
        except Exception as e:  # record, keep sweeping
            r = {"kernel": name, "tflops": 0.0, "error": str(e)[:160]}
        rows.append(r)
        print(f"{r['kernel']:55s} {r['tflops']:9.1f} TF/s"
              + (f"  ERROR {r['error']}" if r.get("error") else ""), flush=True)
    print(json.dumps({"metric": "conv_experiments", "rows": rows}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
