"""Control-plane e2e: an oversized gang vs a small fake topology, observed
end to end over REAL HTTP (ISSUE 5 acceptance criteria, CI job
control-plane-e2e).

Boots a Store + apiserver App (with the ops endpoints mounted) on a real
listener and an in-process Manager running the gang scheduler + podlet
against the same store, then via HTTP:

1. POSTs two 4-chip v5e nodes and a 2-member gang asking 16 chips/pod,
2. polls ``GET /debug/scheduler?gang=...`` until the flight recorder holds
   >= 3 unschedulable decisions, and asserts every candidate node is named
   with the machine-readable verdict ``insufficient_chips`` (free 4 < need 16),
3. LISTs Events and asserts each gang member carries exactly ONE aggregated
   ``FailedScheduling`` Warning from ``tpu-scheduler`` with count > 1 —
   retries bump the counter instead of spamming new objects,
4. scrapes ``/metrics`` for the decision/workqueue/apiserver series the
   cycle must have produced.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only,
~seconds: two fake nodes, one doomed gang, small backoff.
"""

from __future__ import annotations

import json
import sys
import time
import urllib.request

GANG = "huge"
GANG_KEY = f"default/{GANG}"
MEMBERS = ("huge-0", "huge-1")
NODE_CHIPS = 4
POD_CHIPS = 16
MIN_DECISIONS = 3


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=30) as resp:
        return resp.read()


def _post(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url, json.dumps(body).encode(), {"content-type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def _poll(fn, timeout: float = 30.0, interval: float = 0.1, desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _metric_value(text: str, name: str, **labels) -> float:
    """Sum of series for ``name`` whose label set includes ``labels``."""
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue  # e.g. name_bucket / name_count suffixes
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def run() -> dict:
    from kubeflow_tpu.apiserver.server import make_apiserver_app
    from kubeflow_tpu.apiserver.store import Store
    from kubeflow_tpu.controllers.builtin import PodletReconciler, make_tpu_node
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.obs import mount_observability
    from kubeflow_tpu.scheduler import SchedulerReconciler
    from kubeflow_tpu.scheduler.gang import POD_GROUP_LABEL, POD_GROUP_SIZE_ANNOTATION
    from kubeflow_tpu.tpu.topology import RESOURCE_TPU

    store = Store()
    mgr = Manager(store)
    mgr.add(SchedulerReconciler(
        assembly_timeout=5.0, reservation_ttl=5.0,
        backoff_base=0.05, backoff_cap=0.4))
    mgr.add(PodletReconciler())

    app = make_apiserver_app(store)
    mount_observability(app)
    httpd = app.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    mgr.start()
    try:
        # -- populate over HTTP: topology first, then the doomed gang --------
        nodes = []
        for i in range(2):
            node = make_tpu_node(f"tpu-node-{i}", "v5e", "2x4", NODE_CHIPS)
            _post(f"{base}/api/v1/nodes", node)
            nodes.append(node["metadata"]["name"])
        for name in MEMBERS:
            _post(f"{base}/api/v1/namespaces/default/pods", {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": name,
                    "namespace": "default",
                    "labels": {POD_GROUP_LABEL: GANG},
                    "annotations": {POD_GROUP_SIZE_ANNOTATION: str(len(MEMBERS))},
                },
                "spec": {"containers": [{
                    "name": "trainer",
                    "resources": {"limits": {RESOURCE_TPU: str(POD_CHIPS)}},
                }]},
            })

        # -- flight recorder over HTTP ---------------------------------------
        def unschedulable_decisions():
            doc = json.loads(_get(
                f"{base}/debug/scheduler?gang={GANG_KEY}&limit=512"))
            hits = [d for d in doc["decisions"]
                    if d["outcome"] == "unschedulable"]
            return hits if len(hits) >= MIN_DECISIONS else None

        decisions = _poll(
            unschedulable_decisions, timeout=30.0,
            desc=f">= {MIN_DECISIONS} unschedulable decisions for {GANG_KEY}")
        last = decisions[-1]
        verdicts = {n["node"]: n for n in last.get("nodes", [])}
        assert set(verdicts) == set(nodes), \
            f"flight recorder must name every candidate node: {sorted(verdicts)}"
        for node_name, verdict in verdicts.items():
            assert verdict["reason"] == "insufficient_chips", (node_name, verdict)
            assert verdict["capacity"] == NODE_CHIPS and verdict["needed"] == POD_CHIPS, verdict
        assert last["attempt"] >= 1 and last["backoffSeconds"] > 0, last
        assert "insufficient chips" in last["message"], last["message"]

        # -- aggregated Events over HTTP -------------------------------------
        events = json.loads(
            _get(f"{base}/api/v1/namespaces/default/events"))["items"]
        counts = {}
        for member in MEMBERS:
            failed = [e for e in events
                      if (e.get("involvedObject") or {}).get("name") == member
                      and e.get("reason") == "FailedScheduling"]
            assert len(failed) == 1, \
                f"{member}: want ONE aggregated FailedScheduling, got {len(failed)}"
            ev = failed[0]
            assert ev["type"] == "Warning", ev
            assert ev["source"]["component"] == "tpu-scheduler", ev["source"]
            assert ev["count"] > 1, \
                f"{member}: retries must aggregate (count={ev['count']})"
            counts[member] = ev["count"]

        # -- metrics scrape ---------------------------------------------------
        text = _get(f"{base}/metrics").decode()
        decision_total = _metric_value(
            text, "scheduler_decision_total",
            outcome="unschedulable", reason="insufficient_chips")
        assert decision_total >= MIN_DECISIONS, \
            f"scheduler_decision_total(unschedulable)={decision_total}"
        assert _metric_value(
            text, "workqueue_adds_total", queue="SchedulerReconciler") > 0
        assert "workqueue_depth{" in text and "workqueue_unfinished_work_seconds{" in text
        assert _metric_value(
            text, "apiserver_request_seconds_count", verb="create", resource="pods") >= len(MEMBERS)
        assert _metric_value(text, "apiserver_inflight_requests", verb="create") == 0

        return {
            "ok": True,
            "unschedulable_decisions": len(decisions),
            "verdicts": {n: v["reason"] for n, v in verdicts.items()},
            "event_counts": counts,
            "decision_total": decision_total,
        }
    finally:
        httpd.close()
        mgr.stop()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
