"""Retry helper — the analog of testing/run_with_retry.py:1-30 and the
request retry loop in testing/test_tf_serving.py:108-127 (10 attempts,
fixed sleep, last error re-raised)."""

from __future__ import annotations

import logging
import time
from typing import Callable, Tuple, Type, TypeVar

log = logging.getLogger("kubeflow_tpu.e2e")

T = TypeVar("T")


def run_with_retry(
    fn: Callable[[], T],
    retries: int = 10,
    delay: float = 0.5,
    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
) -> T:
    last: BaseException = RuntimeError("run_with_retry: zero attempts")
    for attempt in range(retries):
        try:
            return fn()
        except retry_on as e:
            last = e
            log.debug("attempt %d/%d failed: %s", attempt + 1, retries, e)
            if attempt < retries - 1:
                time.sleep(delay)
    raise last
