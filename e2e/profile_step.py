"""Training-step decomposition: where the ResNet step's time actually goes.

VERDICT r2 #3 asked for a per-op/profile table behind the MFU number. This
backend exposes no per-op trace, so the decomposition is measured the way
the bench measures everything else — each variant scanned inside one
executable with a host-fetch barrier — and each line isolates one
subsystem:

  fwd_eval      forward only, BN in inference mode (no stats writes)
  fwd_train     forward with BN batch stats (adds the normalization pass)
  fwd_bwd       + backward (the conv-transpose/grad convs dominate)
  full_step     + SGD-momentum update (optimizer HBM pass over 25.6M params)

The deltas between lines attribute time: (fwd_train - fwd_eval) ≈ BN stats
cost, (fwd_bwd - 2×fwd) ≈ backward inefficiency beyond the 2× analytic
FLOPs, (full - fwd_bwd) ≈ optimizer + param-cast overhead. Combined with
e2e/ceiling.py's kernel rates this bounds the achievable MFU for this
model family on this chip (the 3x3 convs at ResNet's 64-128 channel widths
sustain 61-93 TF/s of the 197 peak — a 128-wide MXU is half-idle below 128
input channels, so the conv mix itself caps ResNet-50 well under the
theoretical 100%).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict

import jax
import jax.numpy as jnp

try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass


def _scan_time(fn, args, steps: int) -> float:
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: float(jnp.sum(x.astype(jnp.float32))), out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: float(jnp.sum(x.astype(jnp.float32))), out)
    return (time.perf_counter() - t0) / steps


def profile(batch: int = 256, steps: int = 30) -> Dict[str, Any]:
    from kubeflow_tpu.models import ResNet50
    from kubeflow_tpu.training import ClassifierTask
    from kubeflow_tpu.training.classifier import cross_entropy_loss, sgd_momentum

    model = ResNet50(num_classes=1000)
    task = ClassifierTask(model=model, optimizer=sgd_momentum(lr=0.1, total_steps=1000))
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(rng, (batch, 224, 224, 3), jnp.float32)
    labels = jax.random.randint(rng, (batch,), 0, 1000)
    state = task.init(rng, images)
    step = task.make_train_step()

    # Every body perturbs its input by the loop carry (×1e-30, numerically
    # invisible) — without this XLA hoists the whole loop-invariant model
    # call out of the scan and the probe times ONE forward plus adds
    # (measured 4 ms/step for a 2.1 TFLOP forward = impossible 500 TF/s).
    @jax.jit
    def fwd_eval(params, batch_stats, images):
        def body(c, _):
            x = images + c * jnp.float32(1e-30)
            logits = model.apply({"params": params, "batch_stats": batch_stats},
                                 x, train=False)
            return c + jnp.sum(logits.astype(jnp.float32)), ()
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=steps)
        return c

    @jax.jit
    def fwd_train(params, batch_stats, images):
        def body(c, _):
            x = images + c * jnp.float32(1e-30)
            logits, mut = model.apply({"params": params, "batch_stats": batch_stats},
                                      x, train=True, mutable=["batch_stats"])
            extra = sum(jnp.sum(v.astype(jnp.float32))
                        for v in jax.tree_util.tree_leaves(mut))
            return c + jnp.sum(logits.astype(jnp.float32)) + extra, ()
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=steps)
        return c

    @jax.jit
    def fwd_bwd(params, batch_stats, images, labels):
        def body(c, _):
            x = images + c * jnp.float32(1e-30)
            def loss_fn(p):
                logits, _ = model.apply({"params": p, "batch_stats": batch_stats},
                                        x, train=True, mutable=["batch_stats"])
                return cross_entropy_loss(logits, labels)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            gsum = sum(jnp.sum(g.astype(jnp.float32))
                       for g in jax.tree_util.tree_leaves(grads))
            return c + loss + gsum, ()
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=steps)
        return c

    @jax.jit
    def full(state, images, labels):
        def body(s, _):
            s2, m = step(s, images, labels)
            return s2, m["loss"]
        final, losses = jax.lax.scan(body, state, None, length=steps)
        checksum = sum(jnp.sum(p.astype(jnp.float32))
                       for p in jax.tree_util.tree_leaves(final.params))
        return losses[-1], checksum

    rows = {}
    rows["fwd_eval"] = _scan_time(fwd_eval, (state.params, state.batch_stats, images), steps)
    rows["fwd_train"] = _scan_time(fwd_train, (state.params, state.batch_stats, images), steps)
    rows["fwd_bwd"] = _scan_time(fwd_bwd, (state.params, state.batch_stats, images, labels), steps)
    rows["full_step"] = _scan_time(full, (state, images, labels), steps)
    return {"batch": batch, "seconds": rows}


def main() -> None:
    out = profile(batch=int(os.environ.get("PROFILE_BATCH", "256")))
    rows = out["seconds"]
    full = rows["full_step"]
    print(f"{'phase':12s} {'ms/step':>9s} {'of full':>8s}")
    for name, dt in rows.items():
        print(f"{name:12s} {dt * 1e3:8.1f}  {100 * dt / full:7.1f}%")
    bn = rows["fwd_train"] - rows["fwd_eval"]
    bwd = rows["fwd_bwd"] - rows["fwd_train"]
    opt = rows["full_step"] - rows["fwd_bwd"]
    print(f"{'Δ bn_stats':12s} {bn * 1e3:8.1f}  {100 * bn / full:7.1f}%")
    print(f"{'Δ backward':12s} {bwd * 1e3:8.1f}  {100 * bwd / full:7.1f}%")
    print(f"{'Δ optimizer':12s} {opt * 1e3:8.1f}  {100 * opt / full:7.1f}%")
    print(json.dumps({"metric": "resnet50_step_decomposition", **out}))


if __name__ == "__main__":
    main()
