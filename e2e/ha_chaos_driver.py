"""HA chaos e2e: kill -9 the apiserver and the active scheduler mid-wave,
recover from the WAL, fail over the Lease, and lose zero work
(CI job ha-chaos-e2e).

Three real processes beyond this driver: an apiserver running on the
durable WAL+snapshot backend (``APISERVER_WAL_DIR``,
``apiserver/wal.py``) and TWO scheduler replicas under leader election
(``ENABLE_LEADER_ELECTION=true`` — ``runtime/bootstrap.py`` wires the
Lease through the apiserver). The chaos monkey's process-level injectors
(``kill9_apiserver`` / ``kill9_scheduler``, ``runtime/chaos.py``) deliver
real SIGKILLs — no shutdown hook runs, the WAL's fsynced prefix is all
that survives. The storyline:

1. submit the first half of a gang wave; wait until bindings are landing,
2. kill -9 the apiserver mid-wave; restart it against the SAME WAL dir and
   assert recovery: every object back, the RV counter strictly monotonic
   (``/healthz`` exposes it; new writes must mint fresh RVs, never reuse),
   timed as ``recovery_replay_seconds``,
3. assert the ACTIVE scheduler's informers healed across the restart —
   watch reconnect + paginated relist from their durable RVs
   (``informer_watch_reconnects_total`` / ``informer_relists_total`` on
   its /metrics) — riding the client's transient-connection retry,
4. kill -9 the active scheduler; the standby must take over the Lease
   (``leader_election_state{role="scheduler"}`` flips on its /metrics),
   rebuild its ledger from recovered pods, and bind the REST of the wave
   (submitted after the kill): ``failover_to_bind_s`` is kill → last bind,
5. assert zero dropped work (every gang of both halves fully bound) and
   ledger consistency (no node over chip capacity, gangs unsplit where
   sized to fit) from the recovered state.

Exit 0 on success, 1 with a JSON failure report. CPU-only, seconds-scale.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

SEED = 16
#: 16 nodes x 4 chips covers the whole wave's 56-chip demand with packing
#: headroom — zero-dropped-work needs every gang to eventually FIT
NODES = int(os.environ.get("HA_NODES", "16"))
GANGS = int(os.environ.get("HA_GANGS", "6"))
MAX_GANG = int(os.environ.get("HA_MAX_GANG", "4"))
#: fast lease so standby takeover (bounded by lease_duration) stays quick
LEASE_DURATION = os.environ.get("HA_LEASE_DURATION", "2.0")
LEASE_RENEW = os.environ.get("HA_LEASE_RENEW", "0.25")
#: small snapshot interval: the restart must exercise snapshot+tail replay
#: AND push the journal floor past stale informer RVs → 410 → relist
SNAPSHOT_EVERY = os.environ.get("HA_WAL_SNAPSHOT_EVERY", "10")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _metric_value(text: str, name: str, **labels) -> float:
    """Sum of series for ``name`` whose label set includes ``labels``."""
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def _poll(fn, timeout: float = 30.0, interval: float = 0.1,
          desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _healthz_rv(base: str) -> int:
    return int(json.loads(_get(f"{base}/healthz"))["resourceVersion"])


def _scrape(ops: str) -> str:
    try:
        return _get(f"{ops}/metrics", timeout=2.0).decode()
    except (urllib.error.URLError, OSError):
        return ""


def run() -> dict:
    from kubeflow_tpu.apiserver.remote import RemoteStore
    from kubeflow_tpu.runtime.chaos import ChaosMonkey, ChaosSchedule, Fault
    from kubeflow_tpu.scale.loadgen import LoadGenerator
    from kubeflow_tpu.scale.topology import synth_gangs, synthesize
    from kubeflow_tpu.scheduler.gang import POD_GROUP_LABEL
    from kubeflow_tpu.tpu.topology import RESOURCE_TPU

    api_port = _free_port()
    base = f"http://127.0.0.1:{api_port}"
    wal_dir = tempfile.mkdtemp(prefix="ha-chaos-wal-")
    api_env = {**os.environ, "API_PORT": str(api_port),
               "APISERVER_WAL_DIR": wal_dir,
               "APISERVER_WAL_SNAPSHOT_EVERY": SNAPSHOT_EVERY}
    procs: dict = {}
    sched_ops: dict = {}

    def spawn_apiserver() -> None:
        procs["apiserver"] = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.apiserver"], env=api_env)

    def spawn_scheduler(key: str) -> None:
        sched_ops[key] = f"http://127.0.0.1:{_free_port()}"
        procs[key] = subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.scheduler.core"],
            env={**os.environ, "APISERVER_URL": base,
                 "METRICS_PORT": sched_ops[key].rsplit(":", 1)[1],
                 "ENABLE_LEADER_ELECTION": "true",
                 "LEASE_DURATION": LEASE_DURATION,
                 "LEASE_RENEW_INTERVAL": LEASE_RENEW})

    def leading(key: str) -> bool:
        return _metric_value(_scrape(sched_ops[key]),
                             "leader_election_state", role="scheduler") >= 1.0

    def active_scheduler() -> str:
        for key in ("scheduler-a", "scheduler-b"):
            if procs[key].poll() is None and leading(key):
                return key
        return ""

    try:
        spawn_apiserver()
        RemoteStore(base).wait_ready(timeout=60.0)
        spawn_scheduler("scheduler-a")
        spawn_scheduler("scheduler-b")
        # the monkey resolves procs lazily so restarted processes are seen
        monkey = ChaosMonkey(None, ChaosSchedule([]),
                             procs={"apiserver": lambda: procs["apiserver"],
                                    "scheduler-a": lambda: procs["scheduler-a"],
                                    "scheduler-b": lambda: procs["scheduler-b"]})
        active = _poll(active_scheduler, timeout=60.0, interval=0.25,
                       desc="one scheduler to win the Lease")
        standby = "scheduler-b" if active == "scheduler-a" else "scheduler-a"

        # -- 1. first half of the wave lands while everything is healthy ----
        topo = synthesize(NODES, seed=SEED)
        gen = LoadGenerator(base, topo, seed=SEED)
        assert gen.register_nodes() == topo.total_nodes
        shapes = synth_gangs(topo, GANGS, seed=SEED, prefix="ha",
                             max_size=MAX_GANG)
        first, second = shapes[:len(shapes) // 2], shapes[len(shapes) // 2:]
        gen.gang_wave(first)
        _poll(lambda: gen.bound_gangs(), timeout=60.0,
              desc="first bindings before the kill")

        # -- 2. kill -9 the apiserver mid-wave; recover from the WAL --------
        # Wait for a snapshot covering every pod write so far: on recovery
        # the journal floor is the newest snapshot's rv, so the scheduler's
        # pod informer (resume rv < floor) deterministically gets 410 and
        # must heal via the paginated relist. Lease renewals (~4 writes/s)
        # push the WAL over the snapshot threshold on their own.
        rv_mark = _healthz_rv(base)

        def _newest_snapshot_rv() -> int:
            rvs = [int(n[len("snapshot_"):-len(".bin")])
                   for n in os.listdir(wal_dir)
                   if n.startswith("snapshot_") and n.endswith(".bin")]
            return max(rvs, default=0)

        _poll(lambda: _newest_snapshot_rv() >= rv_mark, timeout=60.0,
              interval=0.25, desc="a snapshot past the wave's last write")
        rv_before = _healthz_rv(base)
        heal_base = {
            "reconnects": _metric_value(_scrape(sched_ops[active]),
                                        "informer_watch_reconnects_total"),
            "relists": _metric_value(_scrape(sched_ops[active]),
                                     "informer_relists_total"),
        }
        monkey.inject(Fault(at=0.0, kind="kill9_apiserver"))
        assert procs["apiserver"].poll() is not None, "SIGKILL must be fatal"
        t0 = time.monotonic()
        spawn_apiserver()
        RemoteStore(base).wait_ready(timeout=60.0)
        recovery_replay_seconds = time.monotonic() - t0
        rv_after = _healthz_rv(base)
        assert rv_after >= rv_before, (
            f"recovered RV counter went backwards: {rv_after} < {rv_before}")
        # a fresh write must mint an RV strictly above everything pre-crash
        marker = json.dumps({"apiVersion": "v1", "kind": "ConfigMap",
                             "metadata": {"name": "ha-rv-probe",
                                          "namespace": "default"}}).encode()
        req = urllib.request.Request(
            f"{base}/api/v1/namespaces/default/configmaps", data=marker,
            headers={"content-type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=10.0) as resp:
            minted = int(json.loads(resp.read())["metadata"]["resourceVersion"])
        assert minted > rv_before, (minted, rv_before)
        # zero dropped writes: every pre-crash pod recovered from the WAL
        recovered = gen._list_pods()
        want_pods = sum(s.size for s in first)
        got = [p for p in recovered
               if (p["metadata"].get("labels") or {}).get(POD_GROUP_LABEL)]
        assert len(got) == want_pods, (
            f"WAL recovery dropped pods: {len(got)}/{want_pods}")

        # -- 3. the active scheduler's informers heal across the restart ----
        def informers_healed():
            text = _scrape(sched_ops[active])
            return (_metric_value(text, "informer_watch_reconnects_total")
                    > heal_base["reconnects"]
                    and _metric_value(text, "informer_relists_total")
                    > heal_base["relists"])

        _poll(informers_healed, timeout=60.0, interval=0.25,
              desc="active scheduler informer reconnect+relist")

        # -- 4. kill -9 the active scheduler; the standby finishes the wave --
        monkey.inject(Fault(at=0.0, kind="kill9_scheduler", target=active))
        assert procs[active].poll() is not None, "SIGKILL must be fatal"
        t_failover = time.monotonic()
        _poll(lambda: leading(standby), timeout=60.0, interval=0.1,
              desc="standby scheduler to take over the Lease")
        gen.gang_wave(second)
        gen.wait_gangs_bound([s.name for s in shapes], timeout_s=120.0)
        failover_to_bind_s = time.monotonic() - t_failover

        # -- 5. zero dropped work + consistent ledger from recovered pods ---
        pods = gen._list_pods()
        by_gang: dict = {}
        used: dict = {}
        for pod in pods:
            gang = (pod["metadata"].get("labels") or {}).get(POD_GROUP_LABEL)
            node = (pod.get("spec") or {}).get("nodeName")
            if not gang:
                continue
            assert node, f"unbound pod after recovery: {pod['metadata']['name']}"
            by_gang.setdefault(gang, []).append(pod)
            chips = int(pod["spec"]["containers"][0]["resources"]["limits"]
                        [RESOURCE_TPU])
            used[node] = used.get(node, 0) + chips
        for shape in shapes:
            assert len(by_gang.get(shape.name, [])) == shape.size, (
                f"gang {shape.name}: {len(by_gang.get(shape.name, []))}"
                f"/{shape.size} bound — dropped work")
        capacity = {n["metadata"]["name"]:
                    int(n["status"]["allocatable"][RESOURCE_TPU])
                    for n in json.loads(_get(f"{base}/api/v1/nodes"))["items"]}
        for node, chips in used.items():
            assert chips <= capacity[node], (
                f"ledger rebuilt inconsistently: node {node} over capacity "
                f"({chips} > {capacity[node]})")
        # the RV stream stayed strictly monotonic through crash + failover
        rv_final = _healthz_rv(base)
        assert rv_final > minted > rv_before

        return {
            "ok": True,
            "gangs_bound": len(shapes),
            "pods_bound": sum(s.size for s in shapes),
            "recovery_replay_seconds": round(recovery_replay_seconds, 3),
            "failover_to_bind_s": round(failover_to_bind_s, 3),
            "rv": {"before_kill": rv_before, "after_recovery": rv_after,
                   "final": rv_final},
            "active_then": active, "active_now": standby,
        }
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        shutil.rmtree(wal_dir, ignore_errors=True)


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
