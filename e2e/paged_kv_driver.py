"""Paged-KV serving e2e: a 2-replica fleet on the paged/chunked/
speculative decode path driven over REAL HTTP (ISSUE 12 acceptance
criteria, CI job paged-kv-e2e).

Boots a ModelServer hosting a GPT ``GenerativeModel`` (max_seq=512, so
prompts can exceed the largest prefill bucket) whose engine fleet runs
with the paged KV arena, chunked prefill (chunk=64) and a tiny draft
model speculating ``spec_k=4`` tokens per round, then:

1. **Greedy parity, short prompts** — HTTP completions are bit-identical
   to the static ``generate()`` oracle and deterministic across repeats.
2. **Over-bucket prompt via chunked prefill** — a 300-token prompt
   (past the largest prefill bucket, 256) returns 200 with the exact
   oracle completion, and ``serving_prefill_chunks_total`` counts the
   chunks it took.
3. **Interactive latency holds during a long prefill** — chatty 8-token
   prompts POSTed while the 300-token prefill is in flight all return
   200 with exact oracle completions, and every one of them completes
   in less wall time than the long request itself: the long prefill
   never monopolizes the engine loop the way a monolithic prefill
   dispatch would.
4. **Speculation is live** — ``serving_spec_tokens_drafted_total`` and
   ``serving_spec_tokens_accepted_total`` are both nonzero (greedy tiny
   configs accept most drafts; parity in (1)-(3) proves acceptance is
   correct, these counters prove the fast path actually ran).
5. **Arena reclamation** — after the burst drains, every replica's
   ``serving_kv_blocks_used`` gauge is back to zero and
   ``serving_kv_blocks_free`` equals the arena size: no block leaks
   across admit/grant/retire, even with chunked + speculative traffic.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only,
tiny config, ~tens of seconds.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.error
import urllib.request

REPLICAS = 2
SLOTS = 4
BUDGET = 24
PREFILL_CHUNK = 64
SPEC_K = 4
LONG_PROMPT = 300  # past PREFILL_BUCKETS[-1]=256 -> must chunk
CHATTY = 6


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read()


def _post(url: str, body: dict, timeout: float = 300.0) -> tuple:
    """POST returning ``(status, parsed_body)`` — 4xx/5xx are
    observations, not exceptions."""
    req = urllib.request.Request(
        url, json.dumps(body).encode(), {"content-type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = {"raw": raw.decode(errors="replace")}
        return e.code, parsed


def _poll(fn, timeout: float = 30.0, interval: float = 0.05,
          desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _metric_value(text: str, name: str, **labels) -> float:
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.gpt import GptConfig, GptLM, generate
    from kubeflow_tpu.serving.server import GenerativeModel, ModelServer

    cfg = GptConfig(vocab_size=512, d_model=64, n_layers=2, n_heads=4,
                    d_ff=128, max_seq=512)
    draft_cfg = GptConfig(vocab_size=512, d_model=32, n_layers=1, n_heads=2,
                          d_ff=64, max_seq=512)
    params = GptLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    draft_params = GptLM(draft_cfg).init(
        jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32))["params"]

    rng = np.random.default_rng(12)
    long_prompt = rng.integers(1, cfg.vocab_size, size=LONG_PROMPT).tolist()
    chatty_prompts = [rng.integers(1, cfg.vocab_size, size=8).tolist()
                      for _ in range(CHATTY)]

    def oracle(prompt: list) -> list:
        out = generate(cfg, params, np.asarray([prompt], np.int32),
                       max_new_tokens=BUDGET)
        return np.asarray(out)[0].tolist()

    model = GenerativeModel(
        name="gpt", apply_fn=None, params=params, cfg=cfg,
        max_new_tokens=BUDGET, temperature=0.0,
        replicas=REPLICAS, slots=SLOTS,
        prefill_chunk=PREFILL_CHUNK,
        spec_draft=(draft_cfg, draft_params), spec_k=SPEC_K)
    server = ModelServer()
    server.add(model)
    httpd = server.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    url = f"{base}/v1/models/gpt:predict"
    report: dict = {"ok": True}
    try:
        # -- (0) warm every replica's compile cache -------------------------
        # jit caches are per-engine; without this, the timed phase below
        # would race warm-replica traffic against cold-replica XLA compiles
        # and measure the compiler, not the interleaving.
        fleet = model._continuous_engine()
        for h in fleet.live_handles():
            h.engine.prewarm(8, timeout=300)  # chatty bucket, every group n
            h.engine.submit(np.asarray(long_prompt, np.int32),  # chunk path
                            max_new_tokens=BUDGET).result(timeout=300)

        # -- (1) greedy parity + determinism on short prompts ---------------
        short_ref = oracle(chatty_prompts[0])
        for _ in range(3):
            status, out = _post(url, {"instances": [chatty_prompts[0]]})
            assert status == 200, f"warmup got {status}: {out}"
            assert out["predictions"][0] == short_ref, \
                "paged+spec greedy decode must match the static oracle"

        # -- (2) over-bucket prompt serves through chunked prefill ----------
        long_ref = oracle(long_prompt)
        chunks_before = _metric_value(
            _get(f"{base}/metrics").decode(), "serving_prefill_chunks_total")
        t0 = time.monotonic()
        status, out = _post(url, {"instances": [long_prompt]})
        solo_long_s = time.monotonic() - t0
        assert status == 200, f"over-bucket prompt got {status}: {out}"
        assert out["predictions"][0] == long_ref, \
            "chunked prefill must be bit-identical to the static oracle"
        chunks_after = _metric_value(
            _get(f"{base}/metrics").decode(), "serving_prefill_chunks_total")
        min_chunks = LONG_PROMPT // PREFILL_CHUNK
        assert chunks_after - chunks_before >= min_chunks, \
            f"expected >= {min_chunks} prefill chunks, " \
            f"counter moved {chunks_after - chunks_before}"
        report["long_prompt"] = {"seconds": round(solo_long_s, 3),
                                 "chunks": chunks_after - chunks_before}

        # -- (3) chatty traffic stays fast while a long prefill is in flight
        chatty_refs = [oracle(p) for p in chatty_prompts]
        long_wall = [None]
        chatty_out: list = [None] * CHATTY

        def long_client() -> None:
            t = time.monotonic()
            long_wall[0] = (_post(url, {"instances": [long_prompt]}),
                            time.monotonic() - t)

        def chatty_client(i: int) -> None:
            t = time.monotonic()
            chatty_out[i] = (_post(url, {"instances": [chatty_prompts[i]]}),
                             time.monotonic() - t)

        lt = threading.Thread(target=long_client)
        lt.start()
        time.sleep(0.05)  # let the long prefill admit first
        cts = [threading.Thread(target=chatty_client, args=(i,))
               for i in range(CHATTY)]
        for t in cts:
            t.start()
        lt.join(timeout=300)
        for t in cts:
            t.join(timeout=300)
        assert not lt.is_alive() and not any(t.is_alive() for t in cts), \
            "client threads hung"
        (l_status, l_out), l_seconds = long_wall[0]
        assert l_status == 200, f"long prompt under load got {l_status}"
        assert l_out["predictions"][0] == long_ref
        chatty_seconds = []
        for i, ((status, out), seconds) in enumerate(chatty_out):
            assert status == 200, f"chatty[{i}] got {status}: {out}"
            assert out["predictions"][0] == chatty_refs[i], \
                f"chatty[{i}] diverged from the static oracle under load"
            chatty_seconds.append(seconds)
        report["mixed"] = {
            "long_s": round(l_seconds, 3),
            "chatty_max_s": round(max(chatty_seconds), 3),
            "chatty_p50_s": round(sorted(chatty_seconds)[CHATTY // 2], 3)}

        # -- (3b) interactive TTFT holds on the replica running the prefill
        # The interleaving contract, measured where it is deterministic:
        # submit the long prompt and then chatty prompts to the SAME engine
        # and compare per-request first-token latencies. A monolithic
        # prefill would hold every chatty first token hostage for the whole
        # prompt; chunked prefill admits and decodes them between chunks,
        # so chatty TTFT must come in under the long request's own TTFT
        # (which by construction spans all its chunks).
        h = fleet.live_handles()[0]
        long_req = h.engine.submit(np.asarray(long_prompt, np.int32),
                                   max_new_tokens=BUDGET)
        time.sleep(0.05)  # let the chunked prefill take the floor
        chatty_reqs = [h.engine.submit(np.asarray(p, np.int32),
                                       max_new_tokens=BUDGET)
                       for p in chatty_prompts[:3]]
        assert long_req.result(timeout=300) == long_ref[LONG_PROMPT:]
        for i, r in enumerate(chatty_reqs):
            assert r.result(timeout=300) == chatty_refs[i][8:]
        long_ttft = long_req.first_token_at - long_req.submit_at
        for i, r in enumerate(chatty_reqs):
            ttft = r.first_token_at - r.submit_at
            assert ttft < long_ttft, \
                f"chatty[{i}] TTFT {ttft:.3f}s >= long-prompt TTFT " \
                f"{long_ttft:.3f}s — prefill is not interleaving"
        report["ttft"] = {
            "long_s": round(long_ttft, 3),
            "chatty_max_s": round(max(r.first_token_at - r.submit_at
                                      for r in chatty_reqs), 3)}

        # -- (4) speculation actually ran -----------------------------------
        text = _get(f"{base}/metrics").decode()
        drafted = _metric_value(text, "serving_spec_tokens_drafted_total")
        accepted = _metric_value(text, "serving_spec_tokens_accepted_total")
        assert drafted > 0, "draft model never proposed a token"
        assert 0 < accepted <= drafted, \
            f"accepted={accepted} drafted={drafted}"
        report["spec"] = {"drafted": drafted, "accepted": accepted,
                          "accept_rate": round(accepted / drafted, 3)}

        # -- (5) every KV block reclaimed after the burst -------------------
        def blocks_reclaimed():
            t = _get(f"{base}/metrics").decode()
            return _metric_value(t, "serving_kv_blocks_used") == 0.0

        _poll(blocks_reclaimed, timeout=30.0,
              desc="serving_kv_blocks_used to drain to zero")
        free = _metric_value(_get(f"{base}/metrics").decode(),
                             "serving_kv_blocks_free")
        assert free > 0, "serving_kv_blocks_free gauge missing"
        report["kv_blocks_free_after_drain"] = free
        return report
    finally:
        httpd.close()
        server.close()
        model.close()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
