"""Serving benchmarks on the real chip (VERDICT r2 #4 / round-1 ask #7).

Two rows, mirroring the reference's serving e2e shape
(testing/test_tf_serving.py:108-133 — HTTP predict against a served model):

1. **BERT-base MLM predict over real HTTP**: the model is hosted by
   ModelServer (kubeflow_tpu/serving/server.py) on a local port and driven
   through the same ``/v1/models/<name>:predict`` path users hit. Batch
   buckets 1/8/32; per-request wall latency p50/p99 + throughput. The
   response carries argmax token ids (serving-shaped output, not the
   15 MB/row logits tensor).

2. **GPT KV-cache decode**: prefill a 128-token prompt, then scanned
   single-token steps with the static-shape KV cache
   (models/gpt.py:generate) — steady-state decode tokens/s at batch 1/8.

Run via ``BENCH_MODEL=serving python bench.py`` or directly. Prints a table
plus one JSON line per row; BASELINE.md records the measured numbers.
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

SEQ = 128


def bench_bert_http(batches=(1, 8, 32), requests_per_batch: int = 40) -> List[Dict[str, Any]]:
    import urllib.request

    from kubeflow_tpu.models.bert import BertConfig, BertForMaskedLM
    from kubeflow_tpu.serving.server import ModelServer, ServedModel

    cfg = BertConfig()  # base: 12 layers, hidden 768
    model = BertForMaskedLM(cfg)
    rng = jax.random.PRNGKey(0)
    sample = jax.random.randint(rng, (1, SEQ), 0, cfg.vocab_size)
    params = model.init(rng, sample)["params"]

    def apply_fn(p, ids):
        logits = model.apply({"params": p}, ids)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)  # serving-shaped output

    server = ModelServer()
    server.add(ServedModel(name="bert-base", apply_fn=apply_fn, params=params,
                           input_dtype=jnp.int32))
    httpd = server.app.serve(0)
    url = f"http://127.0.0.1:{httpd.port}/v1/models/bert-base:predict"

    rows = []
    try:
        rng_np = np.random.default_rng(0)
        for batch in batches:
            payload = json.dumps({
                "instances": rng_np.integers(0, cfg.vocab_size, (batch, SEQ)).tolist()
            }).encode()

            def request() -> float:
                t0 = time.perf_counter()
                req = urllib.request.Request(url, payload,
                                             {"content-type": "application/json"})
                with urllib.request.urlopen(req, timeout=120) as resp:
                    body = json.loads(resp.read())
                assert len(body["predictions"]) == batch
                return time.perf_counter() - t0

            request()  # warm: compiles this bucket
            lat = sorted(request() for _ in range(requests_per_batch))
            p50 = statistics.median(lat)
            # With 40 samples, index 37 is a real p95; a "p99" here would
            # just be the max (one tunnel hiccup), so report p95 + max.
            p95 = lat[min(len(lat) - 1, int(len(lat) * 0.95) - 1)]
            rows.append({
                "batch": batch,
                "p50_ms": round(p50 * 1e3, 1),
                "p95_ms": round(p95 * 1e3, 1),
                "max_ms": round(lat[-1] * 1e3, 1),
                "qps": round(1.0 / p50, 2),
                "sequences_per_sec": round(batch / p50, 1),
            })
    finally:
        httpd.close()
    return rows


def bench_gpt_decode(batches=(1, 8), prompt_len: int = 128,
                     new_tokens: int = 256) -> List[Dict[str, Any]]:
    from kubeflow_tpu.models.gpt import GptConfig, GptLM, generate

    cfg = GptConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
                    max_seq=prompt_len + new_tokens, vocab_size=32000)
    rng = jax.random.PRNGKey(0)
    model = GptLM(cfg)
    sample = jax.random.randint(rng, (1, prompt_len), 0, cfg.vocab_size)
    params = model.init(rng, sample)["params"]

    rows = []
    for batch in batches:
        prompt = jax.random.randint(rng, (batch, prompt_len), 0, cfg.vocab_size)
        out = generate(cfg, params, prompt, max_new_tokens=new_tokens)
        np.asarray(out)  # compile + warm, host fetch barrier
        t0 = time.perf_counter()
        out = generate(cfg, params, prompt, max_new_tokens=new_tokens)
        np.asarray(out)
        dt = time.perf_counter() - t0
        rows.append({
            "batch": batch,
            "wall_s": round(dt, 3),
            "decode_tokens_per_sec": round(batch * new_tokens / dt, 1),
            "ms_per_token": round(dt / new_tokens * 1e3, 2),
        })
    return rows


def bench_continuous(slots: int = 8, n_requests: int = 16,
                     prompt_len: int = 128, chunk: int = 16,
                     pipeline: int = 3) -> Dict[str, Any]:
    """Mixed-budget decode workload: continuous batching vs the static
    batch path on the SAME requests (VERDICT r3 #8).

    Budgets cycle [32, 64, 128, 224]: the static path groups ``slots``
    requests per batch and every member pays the group MAX (lockstep
    decode); the continuous engine retires each sequence at ITS budget and
    admits the next from the queue.

    ISSUE-12 knobs (docs/PERFORMANCE.md): ``BENCH_PAGED`` (default 1)
    runs the engine on the paged KV arena, ``BENCH_KV_BLOCKS`` sizes the
    arena (0 = full capacity), ``BENCH_PREFILL_CHUNK`` sets the
    chunked-prefill budget (engine default when unset, 0 disables).
    ``BENCH_SPEC`` (default 1) adds a second timed pass on a speculative
    engine — reporting ``spec_accept_rate`` and ``spec_tokens_per_sec``
    next to the plain numbers, ``BENCH_SPEC_K`` tokens per round.

    ISSUE-18 knobs: ``BENCH_KV_DTYPE`` (default bf16) runs every engine
    on the int8 arena when set to ``int8``. ``BENCH_DRAFT`` (default
    ``distill``) picks the speculative draft: ``distill`` trains a small
    draft from the target with training/distill.py (``BENCH_DISTILL_STEPS``
    KL steps, outside the timed window; on a trained target this is what
    lifts the accept rate past the gate floor), ``self`` keeps the r06
    truncated-layer self-draft (the target's own first ``n_layers // 4``
    blocks with tied embeddings — no second checkpoint, but on-policy
    agreement with the full stack's argmax is poor)."""
    from kubeflow_tpu.models.gpt import GptConfig, GptLM, generate
    from kubeflow_tpu.serving.continuous import ContinuousBatcher

    budgets = [(32, 64, 128, 224)[i % 4] for i in range(n_requests)]
    cfg = GptConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
                    max_seq=prompt_len + max(budgets), vocab_size=32000)
    rng = jax.random.PRNGKey(0)
    model = GptLM(cfg)
    params = model.init(rng, jax.random.randint(rng, (1, prompt_len), 0,
                                                cfg.vocab_size))["params"]
    prompts = [np.asarray(jax.random.randint(jax.random.PRNGKey(i),
                                             (prompt_len,), 0, cfg.vocab_size))
               for i in range(n_requests)]
    total_tokens = sum(budgets)

    # -- static path: batches of `slots`, lockstep to the group max --------
    # warm: compile the per-budget generate programs outside the window.
    # NOTE this path is an OFFLINE ORACLE: it assumes all requests are known
    # upfront and groupable — online it would either wait to fill groups
    # (latency) or run part-empty ones (throughput).
    for b in sorted(set(budgets)):
        np.asarray(generate(cfg, params,
                            np.stack([prompts[0]] * min(slots, n_requests)),
                            max_new_tokens=b))
    t0 = time.perf_counter()
    static_done_at = [0.0] * n_requests
    for lo in range(0, n_requests, slots):
        group = list(range(lo, min(lo + slots, n_requests)))
        group_max = max(budgets[i] for i in group)
        batch = np.stack([prompts[i] for i in group])
        out = generate(cfg, params, batch, max_new_tokens=group_max)
        np.asarray(out)  # host fetch barrier
        for i in group:  # every member waits for the group max (lockstep)
            static_done_at[i] = time.perf_counter() - t0
    static_s = time.perf_counter() - t0

    # -- continuous path: same requests through the slot engine ------------
    paged = os.environ.get("BENCH_PAGED", "1") == "1"
    kv_blocks = int(os.environ.get("BENCH_KV_BLOCKS", "0") or 0) or None
    pc_env = os.environ.get("BENCH_PREFILL_CHUNK", "")
    prefill_chunk = int(pc_env) if pc_env else None
    kv_dtype = os.environ.get("BENCH_KV_DTYPE", "bf16")
    eng = ContinuousBatcher(cfg, params, slots=slots, chunk=chunk,
                            pipeline=pipeline, paged=paged,
                            kv_blocks=kv_blocks, prefill_chunk=prefill_chunk,
                            kv_dtype=kv_dtype)
    try:
        # warm the engine's programs (per-group-size prefill, adopt, and
        # the chunked step) the same way the static path's generate()
        # programs are warmed above — compiles must not sit inside the
        # timed window
        eng.prewarm(prompt_len)
        t0 = time.perf_counter()
        futs = [eng.submit(prompts[i], budgets[i]) for i in range(n_requests)]
        for f in futs:
            f.result(timeout=1800)
        continuous_s = time.perf_counter() - t0
        cont_lat = [f.done_at - t0 for f in futs]
    finally:
        eng.close()

    # SLO quantiles out of the engine's histograms (registry bucket
    # interpolation — the same numbers a /metrics scrape would show).
    # prewarm() runs uninstrumented, so only the timed requests count.
    # Queried BEFORE the speculative pass below adds its own observations.
    from kubeflow_tpu.runtime.metrics import METRICS

    def _q(name: str, q: float) -> float:
        v = METRICS.quantile(name, q)  # None = no observations (not 0.0)
        return round(v, 4) if v is not None else 0.0

    ttft_p50, ttft_p99 = _q("serving_ttft_seconds", 0.5), _q("serving_ttft_seconds", 0.99)
    queue_wait_p99 = _q("serving_queue_wait_seconds", 0.99)

    # -- speculative pass: distilled draft (default) or self-draft ---------
    spec: Dict[str, Any] = {}
    if os.environ.get("BENCH_SPEC", "1") == "1":
        spec_k = int(os.environ.get("BENCH_SPEC_K", "4"))
        draft_mode = os.environ.get("BENCH_DRAFT", "distill")
        if draft_mode == "distill":
            from kubeflow_tpu.training.distill import distill_draft

            # trained OUTSIDE the timed window; the distilled draft is the
            # bench default because the truncated-layer self-draft's accept
            # rate (~0.14 in r06/r07) throws away most speculative compute
            draft_cfg, draft_params = distill_draft(
                cfg, params,
                steps=int(os.environ.get("BENCH_DISTILL_STEPS", "300")),
                seed=0)
            draft_layers = draft_cfg.n_layers
        else:
            draft_layers = max(1, cfg.n_layers // 4)
            draft_cfg = GptConfig(d_model=cfg.d_model, n_layers=draft_layers,
                                  n_heads=cfg.n_heads, d_ff=cfg.d_ff,
                                  max_seq=cfg.max_seq,
                                  vocab_size=cfg.vocab_size)
            draft_params = {k: v for k, v in params.items()
                            if not k.startswith("block_")}
            for i in range(draft_layers):
                draft_params[f"block_{i}"] = params[f"block_{i}"]
        drafted0 = METRICS.counter("serving_spec_tokens_drafted_total").value
        accepted0 = METRICS.counter("serving_spec_tokens_accepted_total").value
        seng = ContinuousBatcher(cfg, params, slots=slots, chunk=chunk,
                                 pipeline=pipeline, paged=paged,
                                 kv_blocks=kv_blocks,
                                 prefill_chunk=prefill_chunk,
                                 kv_dtype=kv_dtype,
                                 spec_draft=(draft_cfg, draft_params),
                                 spec_k=spec_k)
        try:
            seng.prewarm(prompt_len)
            t0 = time.perf_counter()
            futs = [seng.submit(prompts[i], budgets[i])
                    for i in range(n_requests)]
            for f in futs:
                f.result(timeout=1800)
            spec_s = time.perf_counter() - t0
        finally:
            seng.close()
        drafted = METRICS.counter("serving_spec_tokens_drafted_total").value - drafted0
        accepted = METRICS.counter("serving_spec_tokens_accepted_total").value - accepted0
        spec = {
            "spec_k": spec_k,
            "spec_draft": draft_mode,
            "spec_draft_layers": draft_layers,
            "spec_wall_s": round(spec_s, 2),
            "spec_tokens_per_sec": round(total_tokens / spec_s, 1),
            "spec_accept_rate": round(accepted / drafted, 4) if drafted else 0.0,
        }

    return {
        "ttft_p50": ttft_p50,
        "ttft_p99": ttft_p99,
        "queue_wait_p99": queue_wait_p99,
        "paged": paged,
        "kv_blocks": kv_blocks or "full",
        "kv_dtype": kv_dtype,
        "prefill_chunk": eng.prefill_chunk,
        **spec,
        "slots": slots, "requests": n_requests, "budgets": "32/64/128/224",
        "useful_tokens": total_tokens,
        "static_wall_s": round(static_s, 2),
        "static_tokens_per_sec": round(total_tokens / static_s, 1),
        "static_mean_latency_s": round(sum(static_done_at) / n_requests, 2),
        "continuous_wall_s": round(continuous_s, 2),
        "continuous_tokens_per_sec": round(total_tokens / continuous_s, 1),
        "continuous_mean_latency_s": round(sum(cont_lat) / n_requests, 2),
        "speedup": round(static_s / continuous_s, 3),
    }


def bench_disagg(slots: int = 8, n_requests: int = 24,
                 chunk: int = 16, pipeline: int = 3) -> Dict[str, Any]:
    """Heterogeneous-mix serving pass (ISSUE 18): two models multiplexed
    over a disaggregated fleet — a prefill pool and a decode pool per
    model — under the workload that punishes homogeneous replicas most:
    chatty short-prompt decode interleaved with long-prefill requests.

    The fleet runs ``kv_dtype`` from ``BENCH_KV_DTYPE`` (int8 doubles KV
    slots per HBM byte, the r08 default for this pass), routes on the
    per-request ``model`` id, and ships every prefill over the KV wire —
    so the reported aggregate decode tokens/s pays for routing, handoff
    serialization, and import, not just raw decode steps. Headline rows:
    ``decode_tok_s_heterogeneous`` (gate: strictly above the homogeneous
    r06 b8 decode row) and ``kv_handoff_p99_s`` (wire serialization +
    fetch tail). Disable with ``BENCH_DISAGG=0``."""
    from kubeflow_tpu.models.gpt import GptConfig, GptLM
    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.serving.fleet import EngineFleet

    prompt_short, prompt_long, budget = 64, 384, 128
    cfg = GptConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
                    max_seq=prompt_long + budget, vocab_size=32000)
    rng = jax.random.PRNGKey(0)
    model = GptLM(cfg)
    sample = jax.random.randint(rng, (1, prompt_short), 0, cfg.vocab_size)
    params = {
        "alpha": model.init(jax.random.PRNGKey(0), sample)["params"],
        "beta": model.init(jax.random.PRNGKey(1), sample)["params"],
    }
    kv_dtype = os.environ.get("BENCH_KV_DTYPE", "int8")
    fleet = EngineFleet(
        cfg, params["alpha"], max_replicas=4,
        pools={"prefill": 1, "decode": 2},
        models={mid: (cfg, p) for mid, p in params.items()},
        model_slo={"alpha": "interactive", "beta": "batch"},
        slots=slots, chunk=chunk, pipeline=pipeline, name="bench-disagg",
        engine_kwargs={"kv_dtype": kv_dtype,
                       "prefill_chunk": prompt_short})
    # the mix: 2/3 chatty decode, 1/3 long prefill, models alternating
    reqs = []
    for i in range(n_requests):
        plen = prompt_long if i % 3 == 2 else prompt_short
        reqs.append(("alpha" if i % 2 == 0 else "beta",
                     np.asarray(jax.random.randint(
                         jax.random.PRNGKey(100 + i), (plen,), 0,
                         cfg.vocab_size))))
    try:
        # warm both pools' programs for both prompt shapes, per model
        for mid in params:
            for plen in (prompt_short, prompt_long):
                warm = np.asarray(jax.random.randint(
                    jax.random.PRNGKey(plen), (plen,), 0, cfg.vocab_size))
                fleet.submit(warm, 2, model=mid).result(timeout=1800)
        t0 = time.perf_counter()
        futs = [fleet.submit(p, budget, model=mid) for mid, p in reqs]
        for f in futs:
            f.result(timeout=1800)
        wall = time.perf_counter() - t0
        ttfts = sorted(f.first_token_at - f.submit_at for f in futs)
    finally:
        fleet.close()
    handoff_p99 = METRICS.quantile("serving_kv_handoff_seconds", 0.99)
    return {
        "models": 2,
        "pools": {"prefill": 1, "decode": 2},
        "kv_dtype": kv_dtype,
        "requests": n_requests,
        "prompt_mix": f"{prompt_short}/{prompt_long}",
        "budget": budget,
        "wall_s": round(wall, 2),
        "decode_tok_s_heterogeneous": round(n_requests * budget / wall, 1),
        "ttft_p99_s": round(ttfts[min(len(ttfts) - 1,
                                      int(len(ttfts) * 0.99))], 4),
        "kv_handoff_p99_s": (round(handoff_p99, 4)
                             if handoff_p99 is not None else 0.0),
    }


def main() -> int:
    bert = bench_bert_http()
    print(f"{'BERT-base predict (HTTP)':28s} {'p50':>8s} {'p95':>8s} {'max':>8s} {'seq/s':>8s}")
    for r in bert:
        print(f"  batch {r['batch']:<4d}                 {r['p50_ms']:7.1f}ms {r['p95_ms']:7.1f}ms {r['max_ms']:7.1f}ms {r['sequences_per_sec']:8.1f}")
    gpt = bench_gpt_decode()
    print(f"{'GPT-medium KV-cache decode':28s} {'tok/s':>8s} {'ms/tok':>8s}")
    for r in gpt:
        print(f"  batch {r['batch']:<4d}                 {r['decode_tokens_per_sec']:8.1f} {r['ms_per_token']:7.2f}")
    print(json.dumps({"metric": "bert_base_predict_http", "rows": bert, "unit": "ms/qps"}))
    print(json.dumps({"metric": "gpt_medium_kv_decode", "rows": gpt, "unit": "tokens_per_sec"}))
    cont = bench_continuous()
    print(f"{'Continuous vs static batching':28s} {cont['continuous_tokens_per_sec']:8.1f}"
          f" vs {cont['static_tokens_per_sec']:8.1f} tok/s ({cont['speedup']}x)")
    print(json.dumps({"metric": "gpt_continuous_batching", **cont,
                      "unit": "tokens_per_sec"}))
    if os.environ.get("BENCH_DISAGG", "1") == "1":
        dis = bench_disagg()
        print(f"{'Disagg heterogeneous mix':28s} "
              f"{dis['decode_tok_s_heterogeneous']:8.1f} tok/s "
              f"(handoff p99 {dis['kv_handoff_p99_s']}s)")
        print(json.dumps({"metric": "decode_tok_s_heterogeneous",
                          "value": dis["decode_tok_s_heterogeneous"],
                          "unit": "tokens_per_sec", **dis}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
