"""Attribution-plane e2e: a live train loop, profiled over REAL HTTP
(ISSUE 8 acceptance criteria, CI job attribution-e2e).

Runs a tiny jitted train step under ``StepClock`` with the full phase set
(data_wait / compute / fetch), registers the clock at ``/debug/profile``,
mounts observability on a real server, then asserts:

1. ``GET /debug/profile`` returns JSON that ``json.loads`` cleanly and is
   Chrome-trace-loadable: a ``traceEvents`` list with >= 1 complete
   ("ph": "X") event per step phase per captured step plus one per step,
2. capture-on-demand: ``?steps=N&timeout=S`` issued BEFORE the steps run
   blocks until N fresh steps exist and returns exactly their events,
3. ``/metrics`` carries a nonzero ``training_step_peak_hbm_bytes`` gauge
   (the compiled step's memory_analysis footprint),
4. the attribution report's fraction decomposition sums to 1 and its
   measured phases reconstruct the StepClock step within 5%.

Exit 0 on success, 1 with a JSON failure report. CPU, ~seconds.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request

STEPS = 4
PHASES = ("data_wait", "compute", "fetch")


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read()


def run() -> dict:
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.runtime.obs import mount_observability
    from kubeflow_tpu.runtime.tracing import TRACER
    from kubeflow_tpu.tpu.profiling import StepClock, register_profile_clock
    from kubeflow_tpu.training.attribution import (
        attribution_report, price_callable, record_step_peak_hbm)
    from kubeflow_tpu.training.flops import memory_stats
    from kubeflow_tpu.web.http import App

    @jax.jit
    def train_step(w, x):
        return w - 0.01 * jnp.tanh(x @ w).T @ x / x.shape[0]

    rng = jax.random.PRNGKey(0)
    w = jax.random.normal(rng, (64, 64))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 64))

    clock = register_profile_clock(
        StepClock(metrics=METRICS.namespace("training"), tracer=TRACER))
    compiled = train_step.lower(w, x).compile()
    record_step_peak_hbm(memory_stats(compiled))

    def step(w):
        with clock.data_wait():
            time.sleep(0.001)  # stands in for the input pipeline
        with clock.compute():
            w = compiled(w, x)
            jax.block_until_ready(w)
        with clock.fetch():
            float(jnp.sum(w))
        clock.end_step()
        return w

    app = App("attribution-e2e")
    mount_observability(app)
    httpd = app.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    try:
        for _ in range(STEPS):
            w = step(w)

        # -- 1: snapshot profile is valid Chrome trace -----------------------
        doc = json.loads(_get(f"{base}/debug/profile?steps={STEPS}"))
        events = doc["traceEvents"]
        assert doc.get("displayTimeUnit") == "ms", doc.keys()
        complete = [e for e in events if e.get("ph") == "X"]
        for e in complete:
            assert {"name", "ts", "dur", "pid", "tid"} <= set(e), e
        step_events = [e for e in complete if e.get("cat") == "step"]
        assert len(step_events) == STEPS, (len(step_events), STEPS)
        for phase in PHASES:
            n = sum(1 for e in complete
                    if e.get("cat") == "phase" and e["name"] == phase)
            assert n >= STEPS, f"phase {phase}: {n} events < {STEPS} steps"

        # -- 2: capture-on-demand waits for FRESH steps ----------------------
        fresh = 2
        captured = {}

        def capture():
            captured["doc"] = json.loads(
                _get(f"{base}/debug/profile?steps={fresh}&timeout=30"))

        t = threading.Thread(target=capture)
        t.start()
        time.sleep(0.2)  # request must be in its polling wait before we step
        for _ in range(fresh):
            w = step(w)
        t.join(timeout=60)
        assert not t.is_alive(), "on-demand capture never returned"
        got = [e for e in captured["doc"]["traceEvents"]
               if e.get("ph") == "X" and e.get("cat") == "step"]
        assert len(got) == fresh, (len(got), fresh)

        # -- 3: HBM gauge in the exposition ----------------------------------
        text = _get(f"{base}/metrics").decode()
        peak = next((float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                     if ln.startswith("training_step_peak_hbm_bytes")), 0.0)
        assert peak > 0, "training_step_peak_hbm_bytes missing or zero"

        # -- 4: attribution fractions reconstruct the measured step ----------
        cost = price_callable(train_step, w, x, name="train_step",
                              kind="step")
        report = attribution_report([cost], clock=clock)
        frac_sum = sum(report.fractions.values())
        assert abs(frac_sum - 1.0) < 1e-6, report.fractions
        reconstructed = sum(report.measured.values())
        assert abs(reconstructed - report.step_seconds) \
            <= 0.05 * report.step_seconds, (reconstructed, report.step_seconds)
        return {
            "ok": True,
            "steps": STEPS + fresh,
            "trace_events": len(events),
            "peak_hbm_bytes": peak,
            "fractions": {k: round(v, 4) for k, v in report.fractions.items()},
            "step_seconds": round(report.step_seconds, 6),
        }
    finally:
        httpd.close()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
