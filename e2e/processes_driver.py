"""Six-OS-process e2e WITH the apiserver auth gate on (VERDICT r3 #3),
the apiserver REST boundary on TLS (VERDICT r4 #3), and end-user traffic
through the authenticating front gateway (VERDICT r4 #2).

The strongest deployment-shaped check the image allows: every role runs as
its own OS process wired only by HTTPS + env — exactly how the manifests
deploy them — with the apiserver in deny-by-default token/RBAC mode and a
generated cert (web/tls.py) every child verifies via APISERVER_CA_FILE:

  apiserver (HTTPS + APISERVER_AUTH=token, token table from a Secret CSV)
  admission webhook     (own token, group system:kubeflow-tpu; registered
                         dynamically via MutatingWebhookConfiguration)
  substrate controller  (StatefulSet/Deployment/podlet; own token)
  notebook controller   (own token)
  jupyter web app       (own token; trusts ONLY gateway-asserted identity)
  front gateway         (session login -> kubeflow-userid, the Dex/Istio
                         analog — the only identity-header writer)

Flow driven over the wire: anonymous apiserver write -> 401; admin
registers the webhook + creates the namespace + user RoleBinding; the USER
logs in at the gateway and spawns a notebook THROUGH it (per-user SAR on);
a direct-to-JWA request with a hand-written kubeflow-userid is rejected
(spoofed trust root); controllers materialize StatefulSet -> pod (CREATE
through the EXTERNAL webhook); the notebook reaches ready; then the admin
token is ROTATED in the token file mid-run — the old token 401s, the new
one works, no restart (auth.py hot-reload). Run:
    python -m e2e.processes_driver
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List

from .cluster import free_port
from .junit import run_driver

ROLES = {
    "admin": ("e2e-admin", "system:masters"),
    "controllers": ("system:serviceaccount:kubeflow:controllers", "system:kubeflow-tpu"),
    "webhook": ("system:serviceaccount:kubeflow:admission-webhook", "system:kubeflow-tpu"),
    "webapps": ("system:serviceaccount:kubeflow:webapps", "system:kubeflow-tpu"),
}


def _wait_http(url: str, timeout: float = 30.0, context: Any = None) -> None:
    deadline = time.monotonic() + timeout
    last: Any = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2.0, context=context):
                return
        except Exception as e:
            last = e
            time.sleep(0.2)
    raise TimeoutError(f"{url} never became ready: {last}")


def run_processes_e2e(timeout: float = 90.0) -> Dict[str, Any]:
    from kubeflow_tpu.api.meta import REGISTRY, new_object
    from kubeflow_tpu.apiserver.remote import RemoteStore
    from kubeflow_tpu.apiserver.store import ApiError

    from kubeflow_tpu.web.tls import client_context, generate_self_signed

    procs: List[subprocess.Popen] = []
    logs: List[Any] = []
    tokens = {role: f"tok-{role}-{os.getpid()}" for role in ROLES}
    api_port, wh_port, jwa_port, gw_port = free_port(), free_port(), free_port(), free_port()
    api_url = f"https://127.0.0.1:{api_port}"
    user_email = "mluser@example.com"
    user_password = f"pw-{os.getpid()}"
    gw_secret = f"gw-shared-{os.getpid()}"

    common_env: Dict[str, str] = {}  # APISERVER_CA_FILE, once certs exist

    def spawn(tmp: str, mod: str, extra_env: Dict[str, str]) -> subprocess.Popen:
        # scrub ambient auth knobs: stray APISERVER_TOKENS/ANONYMOUS_READ in
        # the outer shell would silently change what this test asserts
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("APISERVER_") and k != "APP_DISABLE_AUTH"}
        env.update({
            "JAX_PLATFORMS": "cpu",  # control-plane roles need no chip
            "APISERVER_URL": api_url,
            "METRICS_PORT": "0",  # ephemeral ops port per process
            "LOG_LEVEL": "WARNING",
            **common_env,
            **extra_env,
        })
        # per-child log FILE, not a pipe: an unread pipe deadlocks a chatty
        # child, and the log carries the diagnostics on failure
        log = open(os.path.join(tmp, mod.rsplit(".", 1)[-1] + ".log"), "w+b")
        logs.append(log)
        p = subprocess.Popen([sys.executable, "-m", mod], env=env,
                             stdout=log, stderr=subprocess.STDOUT)
        procs.append(p)
        return p

    with tempfile.TemporaryDirectory() as tmp:
        token_file = os.path.join(tmp, "tokens.csv")

        def write_tokens(table: Dict[str, str]) -> None:
            # temp + rename: the apiserver hot-reloads on mtime, and a reload
            # that catches a half-written table would transiently 401 roles
            # (the kubelet's Secret remount is atomic the same way)
            with open(token_file + ".tmp", "w") as f:
                for i, (role, (user, group)) in enumerate(ROLES.items()):
                    f.write(f'{table[role]},{user},u{i},"{group}"\n')
            os.replace(token_file + ".tmp", token_file)

        write_tokens(tokens)
        cert_file, key_file = generate_self_signed(tmp)
        ctx = client_context(cert_file)
        common_env["APISERVER_CA_FILE"] = cert_file
        try:
            spawn(tmp, "kubeflow_tpu.apiserver", {
                "API_PORT": str(api_port),
                "APISERVER_AUTH": "token",
                "APISERVER_TOKEN_FILE": token_file,
                "APISERVER_TLS_CERT_FILE": cert_file,
                "APISERVER_TLS_KEY_FILE": key_file,
                # NOTE: no WEBHOOK_URL — admission is registered by writing a
                # MutatingWebhookConfiguration over the wire below (r4 #5)
            })
            _wait_http(f"{api_url}/healthz", context=ctx)
            spawn(tmp, "kubeflow_tpu.webhook", {
                "PORT": str(wh_port), "APISERVER_TOKEN": tokens["webhook"]})
            spawn(tmp, "kubeflow_tpu.controllers.builtin", {
                "APISERVER_TOKEN": tokens["controllers"]})
            spawn(tmp, "kubeflow_tpu.controllers.notebook", {
                "APISERVER_TOKEN": tokens["controllers"]})
            spawn(tmp, "kubeflow_tpu.services.jupyter", {
                "PORT": str(jwa_port),
                "APISERVER_TOKEN": tokens["webapps"],
                # per-user SAR ON; identity accepted only from the gateway
                "GATEWAY_SHARED_SECRET": gw_secret,
            })
            from kubeflow_tpu.services.gateway import hash_password

            spawn(tmp, "kubeflow_tpu.services.gateway", {
                "PORT": str(gw_port),
                "GATEWAY_USERS": f"{user_email}={hash_password(user_password)}",
                "GATEWAY_ROUTES": f"/jupyter=http://127.0.0.1:{jwa_port}",
                "GATEWAY_SHARED_SECRET": gw_secret,
                "GATEWAY_SESSION_KEY": f"sess-{os.getpid()}",
            })
            _wait_http(f"http://127.0.0.1:{wh_port}/healthz")
            _wait_http(f"http://127.0.0.1:{jwa_port}/healthz")
            _wait_http(f"http://127.0.0.1:{gw_port}/healthz")

            # deny-by-default holds on the wire: anonymous write -> 401
            anon = RemoteStore(api_url, token="", ca_file=cert_file)
            try:
                anon.create(new_object("v1", "Namespace", "intruder", None))
                raise AssertionError("unauthenticated write was accepted")
            except ApiError as e:
                assert e.code == 401, f"expected 401, got {e.code}"

            admin = RemoteStore(api_url, token=tokens["admin"], ca_file=cert_file)

            # dynamic admission registration: write the configuration object
            # (failurePolicy Fail — TPU env injection is gang-critical; an
            # unmutated multi-host pod set wedges silently)
            from kubeflow_tpu.apiserver.admission import webhook_configuration

            admin.create(webhook_configuration(
                "poddefault-webhook",
                f"http://127.0.0.1:{wh_port}/apply-poddefault",
                failure_policy="Fail"))
            admin.create(new_object("v1", "Namespace", "team-proc", None))
            # the user needs a platform RoleBinding for the SAR gate (the
            # KFAM contributor path creates exactly this object)
            admin.create({
                "apiVersion": "rbac.authorization.k8s.io/v1", "kind": "RoleBinding",
                "metadata": {"name": "mluser-edit", "namespace": "team-proc"},
                "roleRef": {"kind": "ClusterRole", "name": "kubeflow-edit"},
                "subjects": [{"kind": "User", "name": user_email}],
            })

            import json as _json

            gw_url = f"http://127.0.0.1:{gw_port}"

            # a client that BYPASSES the gateway and hand-writes the
            # identity header must be rejected (Istio-enforcement analog)
            spoof = urllib.request.Request(
                f"http://127.0.0.1:{jwa_port}/api/namespaces/team-proc/notebooks",
                _json.dumps({"name": "spoofed"}).encode(),
                {"content-type": "application/json", "kubeflow-userid": user_email,
                 "cookie": "XSRF-TOKEN=t", "x-xsrf-token": "t"})
            try:
                with urllib.request.urlopen(spoof, timeout=10):
                    raise AssertionError("direct spoofed-header request was accepted")
            except urllib.error.HTTPError as e:
                assert e.code == 401, f"expected 401 for spoofed direct request, got {e.code}"

            # the user logs in at the gateway and spawns THROUGH it
            login = urllib.request.Request(
                f"{gw_url}/login",
                _json.dumps({"email": user_email, "password": user_password}).encode(),
                {"content-type": "application/json"})
            with urllib.request.urlopen(login, timeout=10) as resp:
                assert resp.status == 200
                session = resp.headers["set-cookie"].split(";")[0]

            body = _json.dumps({"name": "proc-nb"}).encode()
            req = urllib.request.Request(
                f"{gw_url}/jupyter/api/namespaces/team-proc/notebooks",
                body, {"content-type": "application/json",
                       "cookie": f"{session}; XSRF-TOKEN=t", "x-xsrf-token": "t"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert resp.status == 200, resp.status

            nb_res = REGISTRY.for_kind("kubeflow.org/v1beta1", "Notebook")
            pod_res = REGISTRY.for_kind("v1", "Pod")
            deadline = time.monotonic() + timeout
            ready = 0
            nb: Dict[str, Any] = {}
            while time.monotonic() < deadline:
                nb = admin.get(nb_res, "proc-nb", "team-proc")
                ready = int((nb.get("status") or {}).get("readyReplicas") or 0)
                if ready >= 1:
                    break
                time.sleep(0.3)
            if ready < 1:
                for log in logs:  # surface child diagnostics in the failure
                    log.flush()
                    log.seek(0)
                    tail = log.read()[-1500:].decode(errors="replace")
                    print(f"--- {log.name} ---\n{tail}", file=sys.stderr)
                raise AssertionError(
                    f"notebook never became ready across 5 processes "
                    f"(status={nb.get('status')})")
            pods = admin.list(pod_res, "team-proc")
            assert any(p["metadata"]["name"].startswith("proc-nb") for p in pods), \
                "no pod materialized for the notebook"

            # -- token rotation mid-run, no apiserver restart (VERDICT r4 #3)
            rotated = dict(tokens)
            rotated["admin"] = f"tok-admin-rotated-{os.getpid()}"
            write_tokens(rotated)
            new_admin = RemoteStore(api_url, token=rotated["admin"], ca_file=cert_file)
            deadline = time.monotonic() + 15.0
            while True:  # hot-reload is mtime-polled (1 s throttle) — poll until it lands
                try:
                    new_admin.get(nb_res, "proc-nb", "team-proc")
                    break
                except ApiError as e:
                    if e.code != 401 or time.monotonic() > deadline:
                        raise
                    time.sleep(0.3)
            try:
                admin.get(nb_res, "proc-nb", "team-proc")
                raise AssertionError("revoked admin token still accepted after rotation")
            except ApiError as e:
                assert e.code == 401, f"expected 401 for revoked token, got {e.code}"

            return {
                "processes": len(procs),
                "auth": "token+rbac deny-by-default",
                "gateway": "session login -> asserted identity; direct spoof 401",
                "transport": "https (generated cert, CA-verified clients)",
                "token_rotation": "revoked 401s, replacement works, no restart",
                "readyReplicas": ready,
                "pods": [p["metadata"]["name"] for p in pods],
            }
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
            for log in logs:
                log.close()


def main(argv=None) -> int:
    def add_args(parser):
        parser.add_argument("--timeout", type=float, default=90.0)

    return run_driver(
        "e2e-processes",
        "ProcessesE2E",
        lambda args: "five-process-auth-on",
        lambda args: lambda: run_processes_e2e(timeout=args.timeout),
        argv=argv,
        add_args=add_args,
        default_junit="junit_processes.xml",
    )


if __name__ == "__main__":
    raise SystemExit(main())
