"""Per-slot KV-write strategies + this backend's dispatch cost model
(VERDICT r4 #2), measured on the real chip.

Round 5's headline finding (this probe, first version): on the tunneled
dev backend ``jax.block_until_ready`` RETURNS EARLY — timings taken with
it were up to 100x optimistic (a 24-layer decode chunk "measured" 0.55 ms
that costs ~150 ms wall). Every number here is therefore synced by a real
host fetch (``np.asarray`` of a small output), and per-op costs come from
CHAINED dispatches divided by the chain length.

The cost model that falls out (and that serving/continuous.py's pipelined
engine is built around):

- dispatch+fetch round trip: ~115 ms FIXED, regardless of payload;
- marginal decode compute: ~2-3 ms/token (GPT-medium, batch 8);
- pipelining hides the RTT: depth-3 overlapped chunks run ~51 ms/chunk
  (16 tokens) vs ~146 ms unpipelined — but a DEEP queue (10+
  outstanding heavy dispatches) degrades ~4x, so depth must stay bounded.

Strategies compared for the per-row cache write itself (the round-4
suspect): where-select over the whole cache, scatter ``.at[arange,
cur].set``, vmapped dynamic_update_slice, and the Pallas row-update
kernel (ops/kv_cache.py). At [8, 352, 16, 64] the whole-cache pass is
~12 MB — sub-ms on-device either way, far below the RTT floor; the
engine-level A/B (KUBEFLOW_TPU_KV_KERNEL=0 vs 1 on
e2e/serving_bench.py:bench_continuous) is the decision-grade comparison.

Run: ``python -m e2e.kv_update_probe``.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

S, T, H, D = 8, 352, 16, 64
CHUNK = 16


def _sync(x) -> None:
    """Order-forcing host fetch: np.asarray of a tiny dependent slice.
    (block_until_ready is NOT a reliable barrier on this backend.)"""
    leaf = jax.tree.leaves(x)[0]
    np.asarray(leaf[(0,) * (leaf.ndim - 1)][:1])


def _chained(fn, cache, new, cur, *, block: int = 8, blocks: int = 6) -> float:
    """Median per-op ms over ``blocks`` chained blocks of ``block`` donated
    dispatches, each block closed by a sync fetch. Chaining amortizes the
    ~115 ms RTT; the block bound keeps the queue shallow (deep queues
    degrade on this backend)."""
    out = fn(cache, new, cur)
    _sync(out)
    times = []
    for _ in range(blocks):
        t0 = time.perf_counter()
        for _ in range(block):
            out = fn(out, new, cur)
        _sync(out)
        times.append((time.perf_counter() - t0) / block)
    return float(np.median(times) * 1e3)


def isolated() -> dict:
    rng = np.random.default_rng(0)
    cache_np = rng.normal(size=(S, T, H, D)).astype(np.float32)
    new = jnp.asarray(rng.normal(size=(S, H, D)), jnp.bfloat16)
    cur = jnp.asarray(rng.integers(0, T, S), jnp.int32)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def select(cache, new, cur):
        at = jnp.arange(T)[None, :, None, None] == cur[:, None, None, None]
        return jnp.where(at, new[:, None], cache)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def scatter(cache, new, cur):
        return cache.at[jnp.arange(S), cur].set(new)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def vmapped_dus(cache, new, cur):
        return jax.vmap(lambda row, n, c: jax.lax.dynamic_update_slice(
            row, n[None], (c, 0, 0)))(cache, new, cur)

    from kubeflow_tpu.ops.kv_cache import kv_row_update

    @functools.partial(jax.jit, donate_argnums=(0,))
    def pallas_row(cache, new, cur):
        return kv_row_update(cache, new, cur)

    out = {}
    for name, fn in [("where_select", select), ("scatter_at", scatter),
                     ("vmapped_dus", vmapped_dus), ("pallas_row", pallas_row)]:
        cache0 = jnp.asarray(cache_np, jnp.bfloat16)  # fresh: prior donated
        out[name + "_ms"] = round(_chained(fn, cache0, new, cur), 3)
    return out


def in_model() -> dict:
    """Engine-shaped measurement: chained chunk dispatches at pipeline
    depth 3 with per-chunk token fetches — exactly the production access
    pattern — for the shared-cursor model, the per-slot select path, and
    the per-slot Pallas-kernel path."""
    from kubeflow_tpu.models.gpt import GptConfig, GptLM

    cfg = GptConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
                    max_seq=T, vocab_size=32000)
    rng = jax.random.PRNGKey(0)
    params = GptLM(cfg).init(rng, jax.random.randint(rng, (1, 128), 0,
                                                     cfg.vocab_size))["params"]

    def fresh_cache(per_slot: bool):
        kv = (S, cfg.max_seq, cfg.n_heads, cfg.head_dim)

        def extra():
            # a FRESH array per block: splicing one shared array object
            # into every block makes 24 duplicate leaves in a donated
            # pytree — double-donation, which this backend surfaces as an
            # InvalidArgument at the next fetch (found the hard way)
            return ({"cursors": jnp.full((S,), 128, jnp.int32)} if per_slot
                    else {"cursor": jnp.full((), 128, jnp.int32)})

        return {f"block_{i}": {"attention": {
            "k": jnp.zeros(kv, cfg.dtype), "v": jnp.zeros(kv, cfg.dtype),
            **extra()}}
            for i in range(cfg.n_layers)}

    def build_chunk_step(per_slot: bool):
        model = GptLM(cfg, decode=True, per_slot=per_slot)

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def step(params, cache, tok):
            def one(carry, _):
                cache, tok = carry
                logits, upd = model.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    mutable=["cache"])
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                return (upd["cache"], nxt), nxt
            (cache, tok), toks = jax.lax.scan(one, (cache, tok), None,
                                              length=CHUNK)
            return cache, tok, jnp.moveaxis(toks, 0, 1)

        return step

    out = {}
    rows = [("shared_cursor", False, None),
            ("per_slot_select", True, "0"),
            ("per_slot_kernel", True, "1")]
    depth, n_chunks = 3, 14
    for name, per_slot, knob in rows:
        if knob is not None:
            os.environ["KUBEFLOW_TPU_KV_KERNEL"] = knob
        step = build_chunk_step(per_slot)
        cache = fresh_cache(per_slot)
        tok = jnp.zeros((S,), jnp.int32)
        cache, tok, toks = step(params, cache, tok)
        np.asarray(toks)  # warm/compile
        t0 = time.perf_counter()
        inflight = []
        for _ in range(n_chunks):
            cache, tok, toks = step(params, cache, tok)
            try:
                toks.copy_to_host_async()
            except Exception:
                pass
            inflight.append(toks)
            if len(inflight) >= depth:
                np.asarray(inflight.pop(0))
        for t in inflight:
            np.asarray(t)
        dt = (time.perf_counter() - t0) / n_chunks
        out[name + "_ms_per_chunk"] = round(dt * 1e3, 1)
        out[name + "_ms_per_token"] = round(dt / CHUNK * 1e3, 3)
    os.environ.pop("KUBEFLOW_TPU_KV_KERNEL", None)
    return out


def main() -> int:
    iso = isolated()
    print("isolated [8,352,16,64] bf16 single-row write (chained, synced):")
    for k, v in iso.items():
        print(f"  {k:20s} {v:8.3f} ms")
    model = in_model()
    print("in-model GPT-medium 24L chunk=16 depth-3 pipeline:")
    for k, v in model.items():
        print(f"  {k:32s} {v:8.3f}")
    print(json.dumps({"metric": "kv_update_probe", **iso, **model}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
