"""Headless DOM harness for the kfui declarative frontend.

The browser-driven e2e tier (reference: testing/test_jwa.py drives JWA with
Selenium through a real browser; centraldashboard/test/e2e.test.ts uses
Puppeteer). This image ships no JS runtime or browser, so the tier is built
the other way around: the frontend expresses ALL of its wiring declaratively
in ``data-kf-*`` attributes (kubeflow_tpu/web/ui/kfui.js is a generic
interpreter with no app logic), and this module interprets the SAME
attribute semantics over a real parsed DOM, driving the real backends
in-process. A flow test here exercises: served HTML → DOM → component init
(fetches) → user interaction (click/fill/submit, confirm dialogs) →
HTTP calls → re-rendered DOM — everything a browser test covers except the
pixel rasterizer and the ~400-line generic runtime, which is kept
app-logic-free precisely so this harness stays faithful.

Semantics mirrored 1:1 from kfui.js (same section names):
templating ``{path}``, items paths with one-level filters
(``tpus[generation={dep}].topologies``), tables with row templates and
show/hide-when, actions with confirm + body templates + then-steps, forms
with dotted names / omit rules, dependent selects, text/show-if binders,
bar charts, the namespace selector, and the exponential-backoff poller.
"""

from __future__ import annotations

import json
import re
from html.parser import HTMLParser
from typing import Any, Callable, Dict, List, Optional, Tuple

VOID_TAGS = {
    "area", "base", "br", "col", "embed", "hr", "img", "input",
    "link", "meta", "param", "source", "track", "wbr",
}


class Element:
    def __init__(self, tag: str, attrs: Dict[str, str], parent: Optional["Element"]):
        self.tag = tag
        self.attrs = dict(attrs)
        self.parent = parent
        self.children: List[Any] = []  # Element | str
        # form-control state
        self.value: str = attrs.get("value", "")
        self.checked: bool = "checked" in attrs
        self.selected_values: List[str] = []
        self._default_value = self.value
        self._default_checked = self.checked

    # -- tree ops ------------------------------------------------------------
    def append(self, node: Any) -> None:
        if isinstance(node, Element):
            node.parent = self
        self.children.append(node)

    def remove(self) -> None:
        if self.parent:
            self.parent.children.remove(self)
            self.parent = None

    def replace_children(self, nodes: List[Any]) -> None:
        for c in self.children:
            if isinstance(c, Element):
                c.parent = None
        self.children = []
        for n in nodes:
            self.append(n)

    def walk(self):
        # Template CONTENT is inert (browsers keep it out of document
        # queries): yield the <template> element itself but never descend
        # into it. A walk started ON a template (materializing a clone)
        # still sees its children.
        for c in list(self.children):
            if isinstance(c, Element):
                yield c
                if c.tag != "template":
                    yield from c.walk()

    # -- queries ---------------------------------------------------------------
    def matches(self, simple: str) -> bool:
        m = re.match(
            r"^([a-zA-Z*][\w-]*)?(?:#([\w-]+))?((?:\.[\w-]+)*)((?:\[[^\]]+\])*)$", simple
        )
        if not m:
            return False
        tag, eid, classes, attrsel = m.groups()
        if tag and tag != "*" and self.tag != tag:
            return False
        if eid and self.attrs.get("id") != eid:
            return False
        for cls in filter(None, (classes or "").split(".")):
            if cls not in (self.attrs.get("class", "").split()):
                return False
        for am in re.findall(r"\[([^\]=]+)(?:=\"?([^\]\"]*)\"?)?\]", attrsel or ""):
            name, want = am
            if name not in self.attrs:
                return False
            if want and self.attrs.get(name) != want:
                return False
        return True

    def css(self, selector: str) -> List["Element"]:
        """Descendant-combinator selector subset (what the pages use)."""
        out: List[Element] = []
        for sel in selector.split(","):
            parts = sel.strip().split()
            candidates: List[Element] = [self]
            for i, part in enumerate(parts):
                nxt: List[Element] = []
                for c in candidates:
                    for el in c.walk():
                        if el.matches(part):
                            nxt.append(el)
                candidates = nxt
            for el in candidates:
                if el not in out:
                    out.append(el)
        return out

    def one(self, selector: str) -> "Element":
        found = self.css(selector)
        if not found:
            raise AssertionError(f"no element matches {selector!r}")
        return found[0]

    def closest(self, pred: Callable[["Element"], bool]) -> Optional["Element"]:
        cur: Optional[Element] = self
        while cur is not None:
            if pred(cur):
                return cur
            cur = cur.parent
        return None

    # -- text ------------------------------------------------------------------
    @property
    def text(self) -> str:
        parts: List[str] = []
        for c in self.children:
            if isinstance(c, str):
                parts.append(c)
            else:
                parts.append(c.text)
        return re.sub(r"\s+", " ", "".join(parts)).strip()

    def set_text(self, value: str) -> None:
        self.replace_children([value])

    def clone(self) -> "Element":
        el = Element(self.tag, dict(self.attrs), None)
        el.value, el.checked = self.value, self.checked
        for c in self.children:
            el.append(c.clone() if isinstance(c, Element) else c)
        return el

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        ident = "#" + self.attrs["id"] if "id" in self.attrs else ""
        return f"<{self.tag}{ident}>"


class _TreeBuilder(HTMLParser):
    def __init__(self) -> None:
        super().__init__(convert_charrefs=True)
        self.root = Element("document", {}, None)
        self.stack = [self.root]

    def handle_starttag(self, tag, attrs):
        el = Element(tag, {k: (v if v is not None else "") for k, v in attrs}, None)
        self.stack[-1].append(el)
        if tag not in VOID_TAGS:
            self.stack.append(el)

    def handle_startendtag(self, tag, attrs):
        self.stack[-1].append(
            Element(tag, {k: (v if v is not None else "") for k, v in attrs}, None)
        )

    def handle_endtag(self, tag):
        for i in range(len(self.stack) - 1, 0, -1):
            if self.stack[i].tag == tag:
                del self.stack[i:]
                return

    def handle_data(self, data):
        if data:
            self.stack[-1].append(data)


def parse_html(html: str) -> Element:
    b = _TreeBuilder()
    b.feed(html)
    return b.root


# ---- kfui semantics ---------------------------------------------------------

def lookup(obj: Any, path: str) -> Any:
    if path in (".", ""):
        return obj
    cur = obj
    for part in path.split("."):
        if cur is None:
            return None
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            return None
    return cur


class Page:
    """One served SPA page wired to in-process backend apps."""

    def __init__(
        self,
        app,
        html: str,
        ns: str = "kubeflow-user",
        headers: Optional[Dict[str, str]] = None,
        extra_apps: Optional[Dict[str, Any]] = None,
    ):
        self.app = app
        self.ns = ns
        self.headers = dict(headers or {})
        self.extra_apps = extra_apps or {}
        self.doc = parse_html(html)
        self.snacks: List[Tuple[str, str]] = []
        self.confirms: List[str] = []
        self.confirm_answer = True
        self.location: Optional[str] = None  # navigation sink
        self.reloaded = False
        self._pollers: Dict[int, "Poller"] = {}
        self.calls: List[Tuple[str, str]] = []  # request log (method, url)
        # Browser-faithful cookie jar: Set-Cookie from responses rides on
        # subsequent requests (session login flows — the gateway tier).
        # _deleted tracks Max-Age=0 deletions so a statically-seeded pair
        # (Page headers) cannot resurrect a cookie the server cleared.
        self.cookies: Dict[str, str] = {}
        self._deleted_cookies: set = set()
        self.init()

    # -- transport (fetch analog, in-process) ---------------------------------
    def api(self, method: str, url: str, body: Any = None):
        # Init-pass GET memo (kfui semantics): components binding the same
        # endpoint during init share one fetch; pollers/actions fetch fresh.
        if method == "GET" and self._init_memo is not None:
            if url not in self._init_memo:
                self._init_memo[url] = self._fetch(method, url, body)
            return self._init_memo[url]
        return self._fetch(method, url, body)

    def _fetch(self, method: str, url: str, body: Any = None):
        self.calls.append((method, url))
        headers = dict(self.headers)
        # one cookie store, jar (fresher) wins over statically-seeded pairs
        effective: Dict[str, str] = {}
        for pair in filter(None, (headers.get("cookie") or "").split(";")):
            name, _, value = pair.strip().partition("=")
            if name:
                effective[name] = value
        effective.update(self.cookies)
        for name in self._deleted_cookies:
            effective.pop(name, None)
        if effective:
            headers["cookie"] = "; ".join(f"{k}={v}" for k, v in effective.items())
        # kfui.js transport: the x-xsrf-token header is read from the
        # XSRF-TOKEN cookie per request (kfui.js cookie("XSRF-TOKEN"))
        if effective.get("XSRF-TOKEN"):
            headers["x-xsrf-token"] = effective["XSRF-TOKEN"]
        resp = self.app.call(method, url, body, headers)
        for raw in getattr(resp, "cookies", []) or []:
            pair = raw.split(";", 1)[0]
            name, _, value = pair.partition("=")
            if name:
                if "max-age=0" in raw.lower():
                    self.cookies.pop(name.strip(), None)
                    self._deleted_cookies.add(name.strip())
                else:
                    self.cookies[name.strip()] = value
                    self._deleted_cookies.discard(name.strip())
        data = resp.body
        if isinstance(data, (bytes, str)) and resp.content_type.startswith("application/json"):
            # fetch().json() analog: proxied responses arrive as raw bytes
            data = json.loads(data) if data else None
        if resp.status >= 400:
            msg = (data or {}).get("error") if isinstance(data, dict) else None
            raise RuntimeError(msg or f"HTTP {resp.status}")
        return data

    # -- templating -----------------------------------------------------------
    def subst(self, template: str, ctx: Any) -> str:
        def repl(m):
            path = m.group(1)
            if path == "ns":
                return self.ns
            v = ctx if path == "." else lookup(ctx, path)
            if isinstance(v, bool):  # JSON booleans render as true/false in JS
                return "true" if v else "false"
            return "" if v is None else str(v)

        # Identifier-shaped placeholders only ({.}, {ns}, {status.phase}) —
        # JSON body templates ({"stopped": true}) pass through untouched.
        return re.sub(r"\{(\.|[A-Za-z_$][\w$.]*)\}", repl, str(template))

    def subst_json(self, template: str, ctx: Any) -> str:
        """subst with JSON-escaped values — for data-kf-body templates,
        so quotes/backslashes in data can't break parsing (kfui substJson)."""

        def repl(m):
            path = m.group(1)
            v = self.ns if path == "ns" else (ctx if path == "." else lookup(ctx, path))
            if isinstance(v, bool):
                v = "true" if v else "false"
            s = "" if v is None else str(v)
            return json.dumps(s)[1:-1]

        return re.sub(r"\{(\.|[A-Za-z_$][\w$.]*)\}", repl, str(template))

    def items_at(self, data: Any, path: str, ctx: Any) -> List[Any]:
        if not path or path == ".":
            return data if isinstance(data, list) else []
        cur = data
        for seg in path.split("."):
            if cur is None:
                return []
            m = re.match(r"^([^[]*)(?:\[([^=\]]+)=([^\]]*)\])?$", seg)
            if m.group(1):
                cur = lookup(cur, m.group(1))
            if m.group(2) is not None and isinstance(cur, list):
                want = self.subst(m.group(3), ctx)
                cur = next(
                    (it for it in cur if str(lookup(it, m.group(2))) == want), None
                )
        if cur is None:
            return []
        return cur if isinstance(cur, list) else [cur]

    # -- init (kf.init order) -------------------------------------------------
    def init(self) -> None:
        self._init_memo: Optional[Dict[str, Any]] = {}
        try:
            self._init_all()
        finally:
            self._init_memo = None

    def _init_all(self) -> None:
        # dispatch order comes from kfspec.json's dispatch section — the
        # SAME source the generated block in kfui.js is emitted from
        # (python -m e2e.uidom --gen-dispatch), so the two runtimes cannot
        # disagree about what initializes or in which order. binding=event
        # entries (form/action) are wired at click()/submit() time here.
        for entry in dispatch_table():
            if entry.get("binding") != "init":
                continue
            handler = getattr(self, "_init_" + entry["handler"])
            for n in self.doc.css(entry["selector"]):
                handler(n)

    # -- components -----------------------------------------------------------
    def _init_nav(self, n: Element) -> None:
        n.attrs["href"] = n.attrs["data-kf-nav"] + "?ns=" + self.ns

    def _init_ns_select(self, sel: Element) -> None:
        try:
            data = self.api("GET", "/api/namespaces")
        except RuntimeError:
            data = []
        namespaces = data if isinstance(data, list) else []
        sel.replace_children([])
        for ns in namespaces:
            opt = Element("option", {"value": ns}, None)
            opt.set_text(ns)
            sel.append(opt)
        if self.ns in namespaces:
            sel.value = self.ns

    def _init_options(self, sel: Element) -> None:
        def load():
            spec = sel.attrs["data-kf-options"].split(";")
            url, items_path, value_path = spec[0], spec[1], spec[2]
            label_tpl = spec[3] if len(spec) > 3 else None
            dep_sel = sel.attrs.get("data-kf-depends")
            dep = ""
            if dep_sel:
                dep = self.doc.one(dep_sel).value
            ctx = {"dep": dep}
            data = self.api("GET", self.subst(url, ctx))
            items = self.items_at(data, self.subst(items_path, ctx), ctx)
            keep: List[Element] = []
            if "data-kf-keep-first" in sel.attrs:
                opts = [c for c in sel.children if isinstance(c, Element) and c.tag == "option"]
                if opts:
                    keep = [opts[0].clone()]
            sel.replace_children(list(keep))
            for item in items:
                value = str(item) if value_path == "." else str(lookup(item, value_path))
                opt = Element("option", {"value": value}, None)
                opt.set_text(self.subst(label_tpl, item) if label_tpl else value)
                sel.append(opt)
            if "disabled" in sel.attrs and (items or keep):
                del sel.attrs["disabled"]
            elif not items and not keep:
                sel.attrs["disabled"] = ""
            options = [c for c in sel.children if isinstance(c, Element)]
            values = [o.attrs.get("value", "") for o in options]
            if sel.value not in values:
                sel.value = values[0] if values else ""

        sel._kf_init = load  # type: ignore[attr-defined]
        try:
            load()
        except RuntimeError:
            pass

    def _init_value(self, node: Element) -> None:
        """data-kf-value: set a form control's value (and reset default)
        from config — admin spawner defaults (kfui initValue)."""
        spec = node.attrs["data-kf-value"].split(";")
        url, path = spec[0], spec[1] if len(spec) > 1 else ""
        try:
            data = self.api("GET", self.subst(url, {}))
        except RuntimeError:
            return
        v = lookup(data, path)
        if v is None:
            return
        node.value = str(v)
        node._default_value = str(v)

    def _init_text(self, node: Element) -> None:
        def load():
            spec = node.attrs["data-kf-text"].split(";")
            url, path = spec[0], spec[1] if len(spec) > 1 else ""
            tpl = spec[2] if len(spec) > 2 else None
            if not url:
                node.set_text(self.subst(tpl or "", {}))
                return
            data = self.api("GET", self.subst(url, {}))
            if tpl:
                node.set_text(self.subst(tpl, data))
            else:
                v = lookup(data, path)
                node.set_text("" if v is None else str(v))

        node._kf_init = load  # type: ignore[attr-defined]
        try:
            load()
        except RuntimeError:
            pass

    def _init_show_if(self, node: Element) -> None:
        def load():
            url, path, want = node.attrs["data-kf-show-if"].split(";")
            data = self.api("GET", self.subst(url, {}))
            v = lookup(data, path)
            got = ("true" if v else "false") if isinstance(v, bool) else str(v)
            if got == want:
                node.attrs.pop("hidden", None)
            else:
                node.attrs["hidden"] = ""

        node._kf_init = load  # type: ignore[attr-defined]
        try:
            load()
        except RuntimeError:
            pass

    def _init_chart(self, node: Element) -> None:
        def load():
            url, items_path, label_path, value_path = node.attrs["data-kf-chart"].split(";")
            data = self.api("GET", self.subst(url, {}))
            items = self.items_at(data, items_path, {})
            svg = Element("svg", {"class": "kf-chart"}, None)
            for item in items:
                value = lookup(item, value_path) or 0
                frac = max(0.0, min(1.0, float(value)))
                bar = Element("rect", {"class": "kf-bar", "data-frac": f"{frac:.4f}"}, None)
                label = Element("text", {"class": "kf-bar-label"}, None)
                label.set_text(str(lookup(item, label_path) or ""))
                pct = Element("text", {"class": "kf-bar-pct"}, None)
                pct.set_text(f"{round(frac * 100)}%")
                svg.append(bar)
                svg.append(label)
                svg.append(pct)
            node.replace_children([svg])

        node._kf_refresh = load  # type: ignore[attr-defined]
        poll = int(node.attrs.get("data-kf-poll", "0"))
        if poll > 0:
            self._pollers[id(node)] = Poller(load, poll)
        try:
            load()
        except RuntimeError:
            pass

    def _init_chart_line(self, node: Element) -> None:
        """data-kf-chart-line: rolling time-series — one [0,1] sample per
        series per load into a client-side window (kfui initChartLine;
        reference resource-chart.js keeps the same sliding window)."""
        url, items_path, label_path, value_path = node.attrs["data-kf-chart-line"].split(";")
        window_n = int(node.attrs.get("data-kf-window", "30"))
        node._kf_history = {}  # type: ignore[attr-defined]

        def load():
            data = self.api("GET", self.subst(url, {}))
            for item in self.items_at(data, items_path, {}):
                label = str(lookup(item, label_path))
                try:
                    v = float(lookup(item, value_path) or 0)
                except (TypeError, ValueError):
                    v = 0.0
                v = max(0.0, min(1.0, v))
                h = node._kf_history.setdefault(label, [])  # type: ignore[attr-defined]
                h.append(v)
                if len(h) > window_n:
                    h.pop(0)
            svg = Element("svg", {"class": "kf-chart-line", "viewBox": "0 0 100 44"}, None)
            step = 100.0 / (window_n - 1) if window_n > 1 else 100.0
            for si, (label, h) in enumerate(node._kf_history.items()):  # type: ignore[attr-defined]
                line = Element("polyline", {
                    "class": f"kf-line kf-line-{si % 8}",
                    "data-series": label,
                    "points": " ".join(
                        f"{i * step:.2f},{42 - v * 40:.2f}" for i, v in enumerate(h)),
                }, None)
                text = Element("text", {"class": "kf-line-label"}, None)
                text.set_text(f"{label} {round(h[-1] * 100)}%")
                svg.append(line)
                svg.append(text)
            node.replace_children([svg])

        node._kf_refresh = load  # type: ignore[attr-defined]
        poll = int(node.attrs.get("data-kf-poll", "0"))
        if poll > 0:
            self._pollers[id(node)] = Poller(load, poll)
        try:
            load()
        except RuntimeError:
            pass

    def _init_table(self, node: Element) -> None:
        url = node.attrs["data-kf-table"]
        items_path = node.attrs.get("data-kf-items",
                                    spec_defaults()["items_path"])
        empty_text = node.attrs.get("data-kf-empty",
                                    spec_defaults()["empty_text"])
        page_size = int(node.attrs.get("data-kf-page-size", "0"))
        template = node.one("template[data-kf-row]")
        tbodies = node.css("tbody")
        tbody = tbodies[0] if tbodies else node
        node._kf_page = 0  # type: ignore[attr-defined]
        node._kf_sort = None  # type: ignore[attr-defined]

        def sort_rows(rows):
            s = node._kf_sort  # type: ignore[attr-defined]
            if not s:
                return rows
            path, direction = s
            keyed = []
            for r in rows:
                v = lookup(r, path)
                keyed.append(("" if v is None else v, r))

            def as_num(v):
                try:
                    return float(v) if v != "" else 0.0
                except (TypeError, ValueError):
                    return None

            numeric = all(v == "" or as_num(v) is not None for v, _ in keyed)
            key = (lambda kv: as_num(kv[0]) or 0.0) if numeric else (lambda kv: str(kv[0]))
            return [r for _, r in sorted(keyed, key=key, reverse=direction == "desc")]

        def render_pager(total, pages):
            pagers = node.css("[data-kf-pager]")
            if not pagers:
                return
            pager = pagers[0]
            pager.replace_children([])
            prev = Element("button", {"type": "button", "class": "kf-page-prev"}, None)
            prev.set_text("‹")
            if node._kf_page <= 0:  # type: ignore[attr-defined]
                prev.attrs["disabled"] = ""
            label = Element("span", {"class": "kf-page-label"}, None)
            label.set_text(f"{node._kf_page + 1 if pages else 0}/{pages} ({total})")  # type: ignore[attr-defined]
            nxt = Element("button", {"type": "button", "class": "kf-page-next"}, None)
            nxt.set_text("›")
            if node._kf_page >= pages - 1:  # type: ignore[attr-defined]
                nxt.attrs["disabled"] = ""
            pager.append(prev)
            pager.append(label)
            pager.append(nxt)

        def render(data):
            node._kf_last = data  # type: ignore[attr-defined]
            rows = sort_rows(list(self.items_at(data, items_path, {})))
            total = len(rows)
            if page_size > 0:
                pages = max(1, -(-total // page_size))
                node._kf_page = max(0, min(node._kf_page, pages - 1))  # type: ignore[attr-defined]
                lo = node._kf_page * page_size  # type: ignore[attr-defined]
                rows = rows[lo:lo + page_size]
                render_pager(total, pages)
            tbody.replace_children([])
            if not rows:
                tr = Element("tr", {}, None)
                td = Element("td", {"class": "empty"}, None)
                td.set_text(empty_text)
                tr.append(td)
                tbody.append(tr)
                return
            for row in rows:
                clone = template.clone()
                self._materialize(clone, row)
                for c in list(clone.children):
                    clone.children.remove(c)
                    tbody.append(c)

        def refresh():
            render(self.api("GET", self.subst(url, {})))

        node._kf_render = render  # type: ignore[attr-defined]
        node._kf_refresh = refresh  # type: ignore[attr-defined]
        poll = int(node.attrs.get("data-kf-poll", "0"))
        if poll > 0:
            self._pollers[id(node)] = Poller(refresh, poll)
        try:
            refresh()
        except RuntimeError as e:
            self.snacks.append((str(e), "error"))

    def _materialize(self, fragment: Element, ctx: Any) -> None:
        def walk_text(el: Element):
            el.children = [
                self.subst(c, ctx) if isinstance(c, str) else c for c in el.children
            ]
            for c in el.children:
                if isinstance(c, Element):
                    walk_text(c)

        walk_text(fragment)
        for el in list(fragment.walk()):
            for k in list(el.attrs):
                if "{" in el.attrs[k]:
                    fill = self.subst_json if k == "data-kf-body" else self.subst
                    el.attrs[k] = fill(el.attrs[k], ctx)
            show = el.attrs.get("data-kf-show-when")
            if show is not None:
                got, _, want = show.partition("==")
                if got != want:
                    el.remove()
                    continue
            hide = el.attrs.get("data-kf-hide-when")
            if hide is not None:
                got, _, want = hide.partition("==")
                if got == want:
                    el.remove()
                    continue
            status = el.attrs.get("data-kf-status")
            if status is not None:
                self._apply_status(el, status)


    #: status-icon glyphs (kfui STATUS_GLYPHS parity)
    STATUS_GLYPHS = {
        "running": "●", "ready": "●", "succeeded": "●",
        "waiting": "◌", "pending": "◌", "creating": "◌", "unknown": "◌",
        "failed": "✕", "error": "✕", "stopped": "■",
    }

    def _apply_status(self, el: Element, value: str) -> None:
        key = (value or "unknown").lower()
        classes = el.attrs.get("class", "").split()
        classes += ["kf-status", f"kf-status-{key}"]
        el.attrs["class"] = " ".join(classes)
        if not el.text.strip():
            el.set_text(self.STATUS_GLYPHS.get(key, "●"))
        el.attrs["title"] = value

    # -- interactions ----------------------------------------------------------
    def _run_then(self, then_spec: Optional[str], result: Any = None) -> None:
        if not then_spec or then_spec == "none":
            return
        for step in then_spec.split(","):
            verb, _, arg = step.partition(":")
            if verb == "refresh":
                target = self.doc.one(arg)
                fn = getattr(target, "_kf_refresh", None) or getattr(target, "_kf_init", None)
                if fn:
                    fn()
            elif verb == "render":
                # render the mutation's own (barrier'd) response — no refetch
                target = self.doc.one(arg)
                fn = getattr(target, "_kf_render", None)
                if fn:
                    fn(result)
            elif verb == "reload":
                self.reloaded = True
            elif verb == "nav":
                self.location = self.subst(arg, {})
            elif verb == "clear":
                for field in self.doc.one(arg).css("[name]"):
                    field.value = field._default_value
                    field.checked = field._default_checked
                    field.selected_values = []

    def click(self, target) -> None:
        """Click: data-kf-action element, th[data-kf-sort], or pager button."""
        el = target if isinstance(target, Element) else self.doc.one(target)
        if el.tag == "th" and "data-kf-sort" in el.attrs:
            return self._click_sort(el)
        classes = el.attrs.get("class", "").split()
        if "kf-page-prev" in classes or "kf-page-next" in classes:
            return self._click_pager(el, +1 if "kf-page-next" in classes else -1)
        # attrs were ctx-resolved in place at materialize time
        attrs = el.attrs
        action = attrs.get("data-kf-action")
        assert action, f"{el!r} has no data-kf-action"
        method, _, url_tpl = action.partition(":")
        url = self.subst(url_tpl, {})
        confirm = attrs.get("data-kf-confirm")
        if confirm:
            self.confirms.append(self.subst(confirm, {}))
            if not self.confirm_answer:
                return
        body = None
        if attrs.get("data-kf-body"):
            body = json.loads(self.subst(attrs["data-kf-body"], {}))
        try:
            result = self.api(method, url, body)
            self.snacks.append((attrs.get("data-kf-done", "done"), "ok"))
            self._run_then(attrs.get("data-kf-then"), result)
        except RuntimeError as e:
            self.snacks.append((str(e), "error"))

    def _click_sort(self, th: Element) -> None:
        table = th.closest(lambda e: "data-kf-table" in e.attrs)
        assert table is not None, "th[data-kf-sort] outside a data-kf-table"
        path = th.attrs["data-kf-sort"]
        cur = table._kf_sort  # type: ignore[attr-defined]
        direction = "desc" if cur and cur[0] == path and cur[1] == "asc" else "asc"
        table._kf_sort = (path, direction)  # type: ignore[attr-defined]
        for other in table.css("th"):
            other.attrs.pop("aria-sort", None)
        th.attrs["aria-sort"] = "ascending" if direction == "asc" else "descending"
        if getattr(table, "_kf_last", None) is not None:
            table._kf_render(table._kf_last)  # type: ignore[attr-defined]

    def _click_pager(self, btn: Element, delta: int) -> None:
        if "disabled" in btn.attrs:
            return
        table = btn.closest(lambda e: "data-kf-table" in e.attrs)
        assert table is not None, "pager button outside a data-kf-table"
        table._kf_page += delta  # type: ignore[attr-defined]
        table._kf_render(table._kf_last)  # type: ignore[attr-defined]

    #: data-kf-validate rule evaluation (kfui validateField parity);
    #: rules are SPACE-separated — | belongs to regex alternation.
    def _validate_field(self, field: Element) -> Optional[str]:
        rules = field.attrs.get("data-kf-validate", "").split()
        # .lower(): JS String(checked) yields 'true'/'false' — lockstep parity
        v = (str(field.checked).lower() if field.attrs.get("type") == "checkbox"
             else field.value)
        for rule in rules:
            name, _, arg = rule.partition(":")
            if name == "required" and not v:
                return "required"
            if name == "pattern" and v and not re.fullmatch(f"(?:{arg})", v):
                return field.attrs.get("data-kf-error", "invalid format")
            if name in ("min", "max") and v != "":
                try:
                    num = float(v)
                except ValueError:
                    return "must be a number"
                if name == "min" and num < float(arg):
                    return f"min {arg}"
                if name == "max" and num > float(arg):
                    return f"max {arg}"
        return None

    def _validate_form(self, form: Element) -> bool:
        ok = True
        for field in form.css("[data-kf-validate]"):
            parent = field.parent
            siblings = [c for c in parent.children if isinstance(c, Element)]
            idx = siblings.index(field)
            err = siblings[idx + 1] if idx + 1 < len(siblings) else None
            if err is None or "kf-error" not in err.attrs.get("class", "").split():
                err = Element("span", {"class": "kf-error"}, None)
                parent.children.insert(parent.children.index(field) + 1, err)
                err.parent = parent
            msg = self._validate_field(field)
            err.replace_children([msg or ""])
            classes = [c for c in field.attrs.get("class", "").split() if c != "kf-invalid"]
            if msg:
                classes.append("kf-invalid")
                ok = False
            field.attrs["class"] = " ".join(classes)
        return ok

    def form_body(self, form: Element) -> Dict[str, Any]:
        body: Dict[str, Any] = {}
        for field in form.css("[name]"):
            if "disabled" in field.attrs:
                continue
            if field.tag == "select" and "multiple" in field.attrs:
                value: Any = list(field.selected_values)
            elif field.attrs.get("type") == "checkbox":
                value = field.checked
            elif field.attrs.get("type") == "number":
                value = "" if field.value == "" else float(field.value)
            else:
                value = field.value
            omit_if = field.attrs.get("data-kf-omit-if")
            if omit_if is not None and str(value) == omit_if:
                continue
            if value == "" and "data-kf-omit-empty" in field.attrs:
                continue
            unless = field.attrs.get("data-kf-omit-unless")
            if unless:
                deps = form.css(unless) or self.doc.css(unless)
                if not deps or not deps[0].value:
                    continue
            # Dotted names nest; NUMERIC segments index arrays (kfui parity).
            path = field.attrs["name"].split(".")
            cur: Any = body
            for i, seg in enumerate(path[:-1]):
                want_array = path[i + 1].isdigit()
                if seg.isdigit():
                    if not isinstance(cur, list):
                        raise ValueError(
                            f"form name mixes array and object segments: {field.attrs['name']}"
                        )
                    idx = int(seg)
                    while len(cur) <= idx:
                        cur.append([] if want_array else {})
                    cur = cur[idx]
                else:
                    if isinstance(cur, list):
                        raise ValueError(
                            f"form name mixes array and object segments: {field.attrs['name']}"
                        )
                    if seg not in cur:
                        cur[seg] = [] if want_array else {}
                    cur = cur[seg]
            leaf = path[-1]
            if leaf.isdigit() != isinstance(cur, list):
                raise ValueError(
                    f"form name mixes array and object segments: {field.attrs['name']}"
                )
            if leaf.isdigit():
                idx = int(leaf)
                while len(cur) <= idx:
                    cur.append(None)
                cur[idx] = value
            else:
                cur[leaf] = value
        return body

    def submit(self, selector: str) -> None:
        form = self.doc.one(selector)
        if not self._validate_form(form):
            return  # inline errors rendered, no HTTP (kfui parity)
        method, _, url_tpl = form.attrs["data-kf-form"].partition(":")
        try:
            result = self.api(method, self.subst(url_tpl, {}), self.form_body(form))
            self.snacks.append((form.attrs.get("data-kf-done", "created"), "ok"))
            self._run_then(form.attrs.get("data-kf-then"), result)
        except RuntimeError as e:
            self.snacks.append((str(e), "error"))

    def fill(self, selector: str, value: str) -> None:
        self.doc.one(selector).value = value

    def select(self, selector: str, value: str) -> None:
        """Choose an option — asserts it exists (a user can only pick what
        the UI offers), then fires dependent reloads (change event)."""
        sel = self.doc.one(selector)
        options = [c for c in sel.children if isinstance(c, Element) and c.tag == "option"]
        values = [o.attrs.get("value", "") for o in options]
        assert value in values, f"option {value!r} not in {values} for {selector}"
        sel.value = value
        if "data-kf-ns-select" in sel.attrs:
            # kfui's change handler navigates with the new ?ns= (initNsSelect
            # edits the full URL via searchParams.set; this harness has no
            # URL bar, so the sink records only the percent-encoded ns pair —
            # fixtures must not assert other query state around it)
            from urllib.parse import quote

            self.location = f"?ns={quote(value)}"
        for other in self.doc.css("[data-kf-depends]"):
            if other.attrs.get("data-kf-depends", "") and self.doc.one(
                other.attrs["data-kf-depends"]
            ) is sel:
                fn = getattr(other, "_kf_init", None)
                if fn:
                    fn()

    def select_multi(self, selector: str, values: List[str]) -> None:
        sel = self.doc.one(selector)
        options = [c for c in sel.children if isinstance(c, Element) and c.tag == "option"]
        have = [o.attrs.get("value", "") for o in options]
        for v in values:
            assert v in have, f"option {v!r} not in {have} for {selector}"
        sel.selected_values = list(values)

    def set_checkbox(self, selector: str, checked: bool) -> None:
        self.doc.one(selector).checked = checked

    # -- observations ----------------------------------------------------------
    def table_rows(self, selector: str) -> List[List[str]]:
        node = self.doc.one(selector)
        tbody = node.css("tbody")[0] if node.css("tbody") else node
        rows = []
        for tr in [c for c in tbody.children if isinstance(c, Element) and c.tag == "tr"]:
            rows.append([td.text for td in tr.css("td")])
        return rows

    def row_button(self, table_sel: str, row_match: str, label: str) -> Element:
        """The action button labeled `label` in the row containing row_match."""
        node = self.doc.one(table_sel)
        for tr in node.css("tr"):
            if row_match in tr.text:
                for btn in tr.css("button"):
                    if btn.text == label:
                        return btn
        raise AssertionError(f"no {label!r} button in a row matching {row_match!r}")

    def text(self, selector: str) -> str:
        return self.doc.one(selector).text

    def visible(self, selector: str) -> bool:
        el = self.doc.one(selector)
        return el.closest(lambda e: "hidden" in e.attrs) is None

    def tick(self, selector: Optional[str] = None) -> None:
        """Advance poll cycles (one tick of every — or one — poller)."""
        if selector:
            node = self.doc.one(selector)
            self._pollers[id(node)].tick()
        else:
            for p in list(self._pollers.values()):
                p.tick()

    def poller_interval(self, selector: str) -> int:
        return self._pollers[id(self.doc.one(selector))].interval


class Poller:
    """kf.poller without timers: exponential backoff, manual ticks
    (exponential-backoff.ts semantics: double on failure, reset on
    success, capped at max)."""

    def __init__(self, fn: Callable[[], None], interval: int,
                 max_interval: Optional[int] = None):
        self.fn = fn
        # kf.poller semantics: a falsy interval/max takes the spec default
        # (|| in the JS — so max_interval=0 must not disable the cap)
        self.base = interval or spec_defaults()["poll_ms"]
        self.max = max_interval or spec_defaults()["poll_max_ms"]
        self.interval = self.base

    def tick(self) -> None:
        try:
            self.fn()
            self.interval = self.base
        except Exception:
            self.interval = min(self.interval * 2, self.max)


# ---------------------------------------------------------------------------
# spec fixtures: the golden corpus shared with kfui.js (VERDICT r3 #4)
# ---------------------------------------------------------------------------

SPEC_PATH = __import__("pathlib").Path(__file__).resolve().parent.parent / \
    "kubeflow_tpu" / "web" / "ui" / "kfspec.json"


_SPEC_CACHE: Optional[Dict[str, Any]] = None


def load_spec() -> Dict[str, Any]:
    global _SPEC_CACHE
    if _SPEC_CACHE is None:
        _SPEC_CACHE = json.loads(SPEC_PATH.read_text())
    return _SPEC_CACHE


def dispatch_table() -> List[Dict[str, str]]:
    """The init dispatch order both runtimes execute (kfspec.json
    dispatch.init_order; kfui.js carries it as a generated block)."""
    return load_spec()["dispatch"]["init_order"]


def spec_defaults() -> Dict[str, Any]:
    """Shared runtime defaults (poll interval/backoff cap, empty-state
    text, items path, snack duration) — single-sourced from kfspec.json."""
    return load_spec()["dispatch"]["defaults"]


def file_sha256(path) -> str:
    import hashlib

    return hashlib.sha256(open(path, "rb").read()).hexdigest()


def lockstep_files() -> Dict[str, Any]:
    """The two implementations of the kfspec contract, keyed as in the
    spec's ``lockstep`` block."""
    here = __import__("pathlib").Path(__file__).resolve()
    return {
        "kfui.js": here.parent.parent / "kubeflow_tpu" / "web" / "ui" / "kfui.js",
        "uidom.py": here,
    }


class CannedApp:
    """Fixture transport: 'METHOD url' -> canned JSON, bodies recorded.

    Quacks like web.http.App.call for exactly what Page._fetch touches."""

    class _Resp:
        def __init__(self, body, status=200):
            self.body = body
            self.status = status

    def __init__(self, responses: Dict[str, Any]):
        self.responses = dict(responses)
        self.bodies: Dict[str, Any] = {}

    def call(self, method: str, url: str, body: Any = None, headers=None):
        key = f"{method} {url.split('?')[0]}" if method == "GET" else f"{method} {url}"
        if method != "GET":
            self.bodies[key] = body
        if key not in self.responses and f"{method} {url}" not in self.responses:
            return self._Resp({"error": f"no canned response for {key}"}, status=404)
        return self._Resp(self.responses.get(key, self.responses.get(f"{method} {url}")))


def run_fixture(fix: Dict[str, Any]) -> Page:
    """Execute one kfspec fixture: DOM-in + canned HTTP -> actions ->
    assertions on DOM-out, recorded calls/bodies/confirms. Raises
    AssertionError with the fixture name on any mismatch."""
    name = fix.get("name", "?")
    app = CannedApp(fix.get("http", {}))
    page = Page(app, fix["html"], ns=fix.get("ns", "team-a"))
    page.confirm_answer = fix.get("confirm_answer", True)
    if "http_after" in fix:
        app.responses.update(fix["http_after"])
    for act in fix.get("actions", []):
        do = act["do"]
        if do == "click":
            page.click(act["target"])
        elif do == "fill":
            page.fill(act["target"], act["value"])
        elif do == "select":
            page.select(act["target"], act["value"])
        elif do == "submit":
            page.submit(act["target"])
        elif do == "tick":
            page.tick(act.get("target"))
        else:
            raise AssertionError(f"{name}: unknown action {do!r}")

    exp = fix.get("expect", {})
    if "calls" in exp:
        got = [f"{m} {u}" for m, u in page.calls]
        assert got == exp["calls"], f"{name}: calls {got} != {exp['calls']}"
    for key, want in (exp.get("bodies") or {}).items():
        assert app.bodies.get(key) == want, \
            f"{name}: body for {key}: {app.bodies.get(key)} != {want}"
    for sel, substr in (exp.get("text") or {}).items():
        els = page.doc.css(sel)
        assert els, f"{name}: no element matches {sel!r}"
        assert substr in els[0].text, f"{name}: {sel!r} text {els[0].text!r} !~ {substr!r}"
    for sel, wants in (exp.get("texts") or {}).items():
        got_texts = [e.text for e in page.doc.css(sel)]
        assert got_texts == wants, f"{name}: texts({sel!r}) = {got_texts} != {wants}"
    for sel, n in (exp.get("count") or {}).items():
        got_n = len(page.doc.css(sel))
        assert got_n == n, f"{name}: count({sel!r}) = {got_n} != {n}"
    for sel in exp.get("absent") or []:
        assert not page.doc.css(sel), f"{name}: {sel!r} unexpectedly present"
    for sel in exp.get("hidden") or []:
        assert not page.visible(sel), f"{name}: {sel!r} unexpectedly visible"
    for sel in exp.get("not_hidden") or []:
        assert page.visible(sel), f"{name}: {sel!r} unexpectedly hidden"
    for sel, attrs in (exp.get("attr") or {}).items():
        el = page.doc.one(sel)
        for k, v in attrs.items():
            assert el.attrs.get(k) == v, \
                f"{name}: {sel!r}[{k}] = {el.attrs.get(k)!r} != {v!r}"
    for sel, v in (exp.get("value") or {}).items():
        el = page.doc.one(sel)
        assert el.value == v, f"{name}: {sel!r}.value = {el.value!r} != {v!r}"
    if "confirms" in exp:
        assert page.confirms == exp["confirms"], \
            f"{name}: confirms {page.confirms} != {exp['confirms']}"
    if "snacks" in exp:
        got_snacks = [s for s, _level in page.snacks]
        assert got_snacks == exp["snacks"], f"{name}: snacks {got_snacks}"
    if "location" in exp:
        assert page.location == exp["location"], f"{name}: location {page.location!r}"
    return page


def sync_spec() -> None:
    """Refresh the lockstep hashes after a deliberate contract change —
    forces whoever edits kfui.js to re-visit uidom.py and the fixtures."""
    global _SPEC_CACHE
    spec = load_spec()
    for key, path in lockstep_files().items():
        spec["lockstep"][key] = file_sha256(path)
    SPEC_PATH.write_text(json.dumps(spec, indent=2) + "\n")
    # drop the cache: later load_spec() calls in this process must re-read
    # the rewritten file, not serve the pre-rewrite (mutated) dict
    _SPEC_CACHE = None
    print(f"lockstep hashes refreshed in {SPEC_PATH}")


_GEN_BEGIN = ("  // BEGIN GENERATED (kfspec.json dispatch; "
              "python -m e2e.uidom --gen-dispatch) — DO NOT EDIT")
_GEN_END = "  // END GENERATED"


def gen_dispatch_js() -> str:
    """The kfui.js dispatch block emitted from kfspec.json: DEFAULTS,
    DISPATCH, and the init loop. The JS runs EVERY entry at init (its
    binding=event handlers wire listeners); uidom interprets the same
    table, dispatching binding=event entries at click()/submit() time."""
    d = load_spec()["dispatch"]
    entries = ",\n".join(
        "    " + json.dumps(e, separators=(", ", ": "))
        for e in d["init_order"])
    return "\n".join([
        _GEN_BEGIN,
        "  kf.DEFAULTS = " + json.dumps(d["defaults"],
                                        separators=(", ", ": ")) + ";",
        "  kf.DISPATCH = [",
        entries + ",",
        "  ];",
        "  kf._initAll = async function (root) {",
        "    for (const entry of kf.DISPATCH) {",
        "      const handler = kf._handlers[entry.handler];",
        "      for (const n of root.querySelectorAll(entry.selector)) "
        "await handler(n);",
        "    }",
        "  };",
        _GEN_END,
    ])


def gen_dispatch() -> bool:
    """Rewrite kfui.js's generated block from the spec; True if changed.
    (tests/test_kfui_spec.py fails when the on-disk block is stale.)"""
    global _SPEC_CACHE
    path = lockstep_files()["kfui.js"]
    src = path.read_text()
    begin = src.index("  // BEGIN GENERATED")
    end = src.index(_GEN_END, begin) + len(_GEN_END)
    new = src[:begin] + gen_dispatch_js() + src[end:]
    if new == src:
        return False
    path.write_text(new)
    # the cached spec's lockstep hash for kfui.js is now stale on disk;
    # force a fresh read so the follow-up sync_spec() hashes the new file
    _SPEC_CACHE = None
    return True


if __name__ == "__main__":
    import sys as _sys

    if "--gen-dispatch" in _sys.argv:
        changed = gen_dispatch()
        print(f"kfui.js dispatch block "
              f"{'regenerated' if changed else 'already current'}")
        if changed:
            sync_spec()
    elif "--sync-spec" in _sys.argv:
        sync_spec()
    else:
        spec = load_spec()
        for fx in spec["fixtures"]:
            run_fixture(fx)
            print(f"fixture ok: {fx['name']}")
