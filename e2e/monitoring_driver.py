"""Monitoring-plane e2e: federation, burn-rate alerting, and scrape-backed
autoscaling over REAL HTTP (ISSUE 10 acceptance criteria, CI job
monitoring-e2e).

Boots THREE distinct processes that each expose /metrics — a ModelServer
hosting a 2-replica tiny-GPT fleet (this process) plus two subprocess
"ops" servers — registers them as annotated Pods in an in-process
apiserver, and drives one MonitoringPlane against the set:

1. **Federation** — the scraper discovers all three targets from Pod
   annotations, ``up == 1`` for each, and ``/federate`` (served over
   HTTP) re-exposes every process's series with instance/job labels in a
   dialect our own parser accepts.
2. **Burn-rate lifecycle** — a slow-replica fault (``step_delay_s``, the
   same knob the chaos monkey's ``slow_replica`` uses) pushes every TTFT
   past the 0.25s threshold; the multi-window burn-rate alert goes
   pending → firing and emits exactly ONE deduplicated Warning Event
   (count > 1); removing the fault and pushing fast traffic resolves it
   (``alerts_firing`` back to 0, a Normal ...Resolved Event).
3. **Scrape-backed autoscaling** — an ``SLOAutoscaler`` reading a
   ``FederatedWindowSource`` (the TSDB, NOT the in-process registry)
   scales the fleet 2 → 3 on the scraped breach.
4. **Dashboard** — ``/api/metrics/platform`` reports the three targets
   and a federated serving p99.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only,
tiny config, ~tens of seconds.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time
import urllib.request

OPS_PROCS = 2
TTFT_THRESHOLD_S = 0.25  # a real TTFT_BUCKETS bound
STEP_DELAY_S = 0.45      # slow-replica fault: every TTFT lands past 0.25s
TICK_S = 0.15

_OPS_SCRIPT = """
import sys, time
from kubeflow_tpu.runtime.metrics import METRICS
from kubeflow_tpu.runtime.obs import mount_observability
from kubeflow_tpu.web.http import App

METRICS.gauge("workqueue_depth", queue="default").set(3)
METRICS.counter("workqueue_adds_total", queue="default").inc(7)
app = App("ops")
mount_observability(app)
srv = app.serve(0)
print(srv.port, flush=True)
time.sleep(600)
"""


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read()


def _post(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url, json.dumps(body).encode(), {"content-type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


class _Traffic:
    """Background request loops so the tick loop never blocks on a slow
    (fault-injected) completion."""

    def __init__(self, url: str, prompt: list, threads: int = 2) -> None:
        self.url = url
        self.prompt = prompt
        self.sent = 0
        self.errors: list = []
        self._stop = threading.Event()
        self._threads = [threading.Thread(target=self._run, daemon=True)
                         for _ in range(threads)]

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                _post(self.url, {"instances": [self.prompt]})
                self.sent += 1
            except Exception as e:  # noqa: BLE001 — recorded, asserted below
                self.errors.append(str(e))
                if len(self.errors) > 10:
                    return

    def __enter__(self) -> "_Traffic":
        for t in self._threads:
            t.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=120)


class _AutoscalerCadence:
    """Tick the autoscaler on its own slow cadence: evaluation windows must
    be long enough to hold traffic (a scrape-rate window of a ~2s/request
    workload is empty more often than not, and an empty-but-fresh window
    legitimately reads as idle)."""

    def __init__(self, autoscaler, every_s: float = 2.5) -> None:
        self.autoscaler = autoscaler
        self.every_s = every_s
        self._last = 0.0

    def maybe_tick(self) -> None:
        now = time.monotonic()
        if now - self._last >= self.every_s:
            self._last = now
            self.autoscaler.tick()


def _tick_until(plane, predicate, timeout: float, desc: str,
                cadence=None) -> list:
    """Drive ``plane.tick()`` (and optionally the autoscaler cadence) on
    real time until ``predicate(statuses)`` holds."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        statuses = plane.tick()
        if cadence is not None:
            cadence.maybe_tick()
        if predicate(statuses):
            return statuses
        time.sleep(TICK_S)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def run() -> dict:
    from kubeflow_tpu.api.meta import new_object
    from kubeflow_tpu.apiserver.client import Client
    from kubeflow_tpu.apiserver.store import Store
    from kubeflow_tpu.monitoring import (
        SCRAPE_ANNOTATION,
        SCRAPE_JOB_ANNOTATION,
        SCRAPE_URL_ANNOTATION,
        BurnRateWindow,
        MonitoringPlane,
        SLOBurnRateAlert,
        parse_exposition,
    )
    from kubeflow_tpu.runtime.obs import mount_observability
    from kubeflow_tpu.serving.autoscaler import (
        AutoscalerConfig,
        FederatedWindowSource,
        SLOAutoscaler,
    )
    from kubeflow_tpu.serving.server import ModelServer, gpt_served_model
    from kubeflow_tpu.services.dashboard import make_dashboard_app
    from kubeflow_tpu.web.auth import AuthConfig
    from kubeflow_tpu.web.http import App

    report: dict = {"ok": True}
    procs: list = []
    closers: list = []
    try:
        # -- three distinct processes exposing /metrics ----------------------
        model = gpt_served_model(name="gpt", tiny=True, max_new_tokens=4,
                                 replicas=2)
        model.max_replicas = 3
        server = ModelServer()
        server.add(model)
        fleet = model._continuous_engine()
        httpd = server.serve(0)
        closers += [httpd.close, server.close, model.close]
        base = f"http://127.0.0.1:{httpd.port}"

        urls = [f"{base}/metrics"]
        for i in range(OPS_PROCS):
            proc = subprocess.Popen(
                [sys.executable, "-c", _OPS_SCRIPT],
                stdout=subprocess.PIPE, text=True)
            procs.append(proc)
            port = int(proc.stdout.readline().strip())
            urls.append(f"http://127.0.0.1:{port}/metrics")

        # -- discovery: three annotated Pods in an in-process apiserver ------
        client = Client(Store())
        for i, url in enumerate(urls):
            job = "serving" if i == 0 else "ops"
            client.create(new_object(
                "v1", "Pod", f"target-{i}", "default",
                annotations={SCRAPE_ANNOTATION: "true",
                             SCRAPE_URL_ANNOTATION: url,
                             SCRAPE_JOB_ANNOTATION: job}))

        plane = MonitoringPlane(client=client, stale_after=3, timeout_s=5.0)
        plane.rules.repeat_s = 1.0  # fast repeat: the dedup assertion needs >=2 emissions
        plane.rules.add(SLOBurnRateAlert(
            name="TtftBurn",
            metric="serving_ttft_seconds",
            threshold_s=TTFT_THRESHOLD_S,
            objective=0.9,
            windows=(BurnRateWindow(short_s=1.5, long_s=4.0, factor=2.0,
                                    severity="page"),),
            for_s=0.2,
        ))

        # -- (1) federation of three processes -------------------------------
        up = plane.scraper.scrape_once()
        assert len(up) == 3 and all(up.values()), f"all targets up: {up}"
        monitor_app = App("monitor")
        mount_observability(monitor_app)
        plane.mount(monitor_app)
        monitor_httpd = monitor_app.serve(0)
        closers.append(monitor_httpd.close)
        fed_url = f"http://127.0.0.1:{monitor_httpd.port}/federate"

        prompt = list(range(1, 9))
        predict = f"{base}/v1/models/gpt:predict"
        for _ in range(4):  # warm-up: fast traffic seeds both SLO histograms
            _post(predict, {"instances": [prompt]})
        plane.tick()
        families = parse_exposition(_get(fed_url).decode())
        by_name = {f.name: f for f in families}
        assert "workqueue_depth" in by_name, "ops subprocess series federated"
        ops_instances = {s.labels["instance"]
                         for s in by_name["workqueue_depth"].samples}
        assert len(ops_instances) == OPS_PROCS, ops_instances
        assert "serving_ttft_seconds" in by_name, "serving histogram federated"
        bucket = by_name["serving_ttft_seconds"].samples[0]
        assert bucket.labels["job"] == "serving"
        assert len({s.labels["instance"] for f in families
                    for s in f.samples if "instance" in s.labels}) == 3, \
            "three distinct processes must federate"
        report["federated_targets"] = sorted(
            lab["instance"] for lab, _t, v in plane.tsdb.latest("up"))
        report["federated_families"] = len(families)

        # -- (2)+(3) burn-rate firing + scrape-backed scale-up ---------------
        autoscaler = SLOAutoscaler(fleet, AutoscalerConfig(
            ttft_slo=TTFT_THRESHOLD_S, queue_wait_slo=10.0, quantile=0.9,
            breach_ticks=2, idle_ticks=10_000, cooldown_ticks=0),
            source=FederatedWindowSource(plane.tsdb))
        cadence = _AutoscalerCadence(autoscaler)
        statuses = plane.tick()
        assert statuses[0]["state"] == "inactive", statuses
        for handle in fleet.live_handles():  # the chaos monkey's slow_replica knob
            handle.engine.step_delay_s = STEP_DELAY_S
        with _Traffic(predict, prompt) as slow_traffic:
            statuses = _tick_until(
                plane, lambda ss: ss[0]["state"] == "firing", 45.0,
                "burn-rate alert to fire", cadence=cadence)
            report["burn_short_at_fire"] = statuses[0]["burn_short"]
            # keep ticking while firing: emissions must AGGREGATE
            _tick_until(plane,
                        lambda ss: _events(client, "TtftBurn")
                        and _events(client, "TtftBurn")[0]["count"] >= 2,
                        20.0, "deduplicated Event count to climb",
                        cadence=cadence)
            _tick_until(plane, lambda ss: fleet.desired_replicas == 3, 60.0,
                        "scrape-backed scale-up 2 -> 3", cadence=cadence)
        assert slow_traffic.errors == [], slow_traffic.errors
        firing_events = _events(client, "TtftBurn")
        assert len(firing_events) == 1, \
            f"firing must dedup to ONE Event, got {len(firing_events)}"
        assert firing_events[0]["count"] >= 2
        assert firing_events[0]["type"] == "Warning"
        assert autoscaler.last["source"] == "federated"
        fleet_doc = json.loads(_get(f"{base}/debug/fleet"))
        assert fleet_doc["desired_replicas"] == 3, fleet_doc
        report["event_count"] = firing_events[0]["count"]
        report["autoscaled_to"] = fleet_doc["desired_replicas"]
        report["autoscaler_source"] = autoscaler.last["source"]
        report["slow_requests"] = slow_traffic.sent

        # -- (2b) recovery resolves the alert --------------------------------
        for handle in fleet.live_handles():
            handle.engine.step_delay_s = 0.0
        with _Traffic(predict, prompt) as fast_traffic:
            statuses = _tick_until(
                plane, lambda ss: ss[0]["state"] == "resolved", 45.0,
                "burn-rate alert to resolve")
        assert fast_traffic.errors == [], fast_traffic.errors
        from kubeflow_tpu.runtime.metrics import METRICS
        assert METRICS.value("alerts_firing", alertname="TtftBurn",
                             severity="page") == 0.0
        resolved = _events(client, "TtftBurnResolved")
        assert len(resolved) == 1 and resolved[0]["type"] == "Normal"
        report["resolved"] = True
        report["fast_requests"] = fast_traffic.sent

        # -- (4) dashboard speaks federated data -----------------------------
        dash = make_dashboard_app(client, auth=AuthConfig(disable_auth=True),
                                  monitoring=plane)
        overview = dash.call("GET", "/api/metrics/platform?window=30",
                             None, {"kubeflow-userid": "ops@example.com"})
        assert overview.status == 200, overview.body
        doc = overview.body
        assert len(doc["targets"]) == 3, doc["targets"]
        assert all(t["up"] == 1.0 for t in doc["targets"]), doc["targets"]
        assert doc["serving"]["ttftP99"] is not None, \
            "platform p99 must come from federated data"
        report["platform_ttft_p99"] = doc["serving"]["ttftP99"]
        return report
    finally:
        for proc in procs:
            proc.terminate()
        for close in closers:
            try:
                close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
        for proc in procs:
            proc.wait(timeout=30)


def _events(client, reason: str) -> list:
    return [e for e in client.list("v1", "Event", "kubeflow-system")
            if e.get("reason") == reason]


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        import traceback
        traceback.print_exc(file=sys.stderr)
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
