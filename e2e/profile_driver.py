"""Profile lifecycle e2e driver — the reference's profiles_test.py
(py/kubeflow/kubeflow/ci/profiles_test.py:1-30) as a standalone driver:

Creation: create a Profile CR, then verify the namespace exists with the
same name, ServiceAccounts ``default-editor``/``default-viewer`` are
created, the owner RoleBinding binds ``kubeflow-admin``, the Istio
AuthorizationPolicy guards the namespace, and the TPU ResourceQuota is
materialized when the spec carries one.

Deletion: delete the Profile and verify namespace + owned objects are gone
(the reference expects ApiException on re-read; here NotFound).

Run standalone:  python -m e2e.profile_driver
"""

from __future__ import annotations

from typing import Any, Dict

from .cluster import E2ECluster, unique_namespace, wait_for_condition
from .junit import run_driver

OWNER = "profile-e2e@example.com"


def run_profile_e2e(timeout: float = 30.0) -> Dict[str, Any]:
    with E2ECluster() as cluster:
        client = cluster.client
        ns = unique_namespace("profile")
        client.create({
            "apiVersion": "kubeflow.org/v1",
            "kind": "Profile",
            "metadata": {"name": ns},
            "spec": {
                "owner": {"kind": "User", "name": OWNER},
                "resourceQuotaSpec": {
                    "hard": {"requests.google.com/tpu": "16"},
                },
            },
        })

        def materialized() -> bool:
            if client.get_opt("v1", "Namespace", ns) is None:
                return False
            sas = {sa["metadata"]["name"]
                   for sa in client.list("v1", "ServiceAccount", ns)}
            if not {"default-editor", "default-viewer"} <= sas:
                return False
            roles = {(rb.get("roleRef") or {}).get("name")
                     for rb in client.list("rbac.authorization.k8s.io/v1", "RoleBinding", ns)}
            return "kubeflow-admin" in roles

        wait_for_condition(materialized, timeout=timeout, desc=f"profile {ns} materialized")

        policies = client.list("security.istio.io/v1beta1", "AuthorizationPolicy", ns)
        assert any(p["metadata"]["name"] == "ns-owner-access-istio" for p in policies), (
            "owner AuthorizationPolicy missing"
        )
        quotas = client.list("v1", "ResourceQuota", ns)
        assert any(
            (q.get("spec") or {}).get("hard", {}).get("requests.google.com/tpu") == "16"
            for q in quotas
        ), "TPU ResourceQuota not materialized"

        # Deletion: profile goes away and takes the namespace contents along.
        client.delete("kubeflow.org/v1", "Profile", ns)
        wait_for_condition(
            lambda: client.get_opt("kubeflow.org/v1", "Profile", ns) is None
            and client.get_opt("v1", "Namespace", ns) is None,
            timeout=timeout,
            desc=f"profile {ns} deleted",
        )
        return {"namespace": ns, "created": True, "deleted": True}


def main(argv=None) -> int:
    return run_driver(
        "e2e-profile",
        "ProfileE2E",
        lambda args: "profile-lifecycle",
        lambda args: lambda: run_profile_e2e(),
        argv=argv,
        default_junit="junit_profile.xml",
    )


if __name__ == "__main__":
    raise SystemExit(main())
