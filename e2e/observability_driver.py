"""Observability-plane e2e: one dryrun serving request, then prove the
whole plane saw it (ISSUE 4 acceptance criteria, CI job observability-e2e).

Drives a tiny GPT servable through ModelServer over REAL HTTP with a fixed
W3C ``traceparent`` header, then asserts:

1. ``/metrics`` is valid exposition carrying nonzero
   ``serving_ttft_seconds`` / ``serving_inter_token_seconds`` /
   ``serving_queue_wait_seconds`` histograms with trace-id exemplars,
2. ``/debug/traces?trace_id=...`` returns ONE trace whose tree is
   client traceparent → HTTP handler span → serving.request span with the
   complete enqueued→admitted→prefill_done→first_token→retired event set.

Exit 0 on success, 1 with a JSON failure report otherwise. Runs on CPU
(JAX_PLATFORMS=cpu) in ~seconds — tiny config, one request.
"""

from __future__ import annotations

import json
import sys
import urllib.request

CLIENT_TRACE_ID = "ab" * 16
CLIENT_SPAN_ID = "cd" * 8
TRACEPARENT = f"00-{CLIENT_TRACE_ID}-{CLIENT_SPAN_ID}-01"

SLO_HISTOGRAMS = (
    "serving_ttft_seconds",
    "serving_inter_token_seconds",
    "serving_queue_wait_seconds",
)


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read()


def run() -> dict:
    from kubeflow_tpu.serving.server import ModelServer, gpt_served_model

    model = gpt_served_model(tiny=True, max_new_tokens=8)
    server = ModelServer()
    server.add(model)
    httpd = server.app.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    try:
        payload = json.dumps({"instances": [[1, 2, 3, 4]]}).encode()
        req = urllib.request.Request(
            f"{base}/v1/models/gpt:predict", payload,
            {"content-type": "application/json", "traceparent": TRACEPARENT})
        with urllib.request.urlopen(req, timeout=120) as resp:
            body = json.loads(resp.read())
        assert body["predictions"] and len(body["predictions"][0]) == 4 + 8, body

        # -- scrape ----------------------------------------------------------
        text = _get(f"{base}/metrics").decode()
        for name in SLO_HISTOGRAMS:
            count = next(
                (float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
                 if ln.startswith(f"{name}_count")), 0.0)
            assert count > 0, f"{name}_count not nonzero in scrape"
            assert f'trace_id="{CLIENT_TRACE_ID}"' in text, \
                f"no exemplar with the client trace id near {name}"

        # -- trace tree ------------------------------------------------------
        doc = json.loads(_get(f"{base}/debug/traces?trace_id={CLIENT_TRACE_ID}"))
        spans = doc["resourceSpans"][0]["scopeSpans"][0]["spans"]
        by_id = {s["spanId"]: s for s in spans}
        request_spans = [s for s in spans if s["name"] == "serving.request"]
        assert len(request_spans) == 1, f"want 1 serving.request, got {len(request_spans)}"
        rs = request_spans[0]
        events = [e["name"] for e in rs.get("events", [])]
        want = ["enqueued", "admitted", "prefill_done", "first_token", "retired"]
        assert [e for e in events if e in want] == want, f"event set {events}"
        # root via traceparent: serving.request -> HTTP handler -> client
        handler = by_id.get(rs.get("parentSpanId", ""))
        assert handler is not None and handler["name"].startswith("model-server"), \
            f"serving.request not parented to the HTTP handler: {rs.get('parentSpanId')}"
        assert handler.get("parentSpanId") == CLIENT_SPAN_ID, \
            "handler span not parented to the client traceparent"
        return {
            "ok": True,
            "trace_id": CLIENT_TRACE_ID,
            "spans": len(spans),
            "events": events,
            "generated": len(body["predictions"][0]),
        }
    finally:
        httpd.close()
        if model._engine is not None:
            model.close()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
