"""Control-plane scale e2e: a seeded synthetic topology driven over REAL
HTTP, asserting the ISSUE 11 observability surface end to end (CI job
controlplane-scale-e2e; periodic run sets SCALE_NODES=5000).

Boots Store + apiserver App on a real listener with the gang scheduler +
podlet reconciling in-process, then via :class:`~kubeflow_tpu.scale.loadgen.
LoadGenerator`:

1. registers a seeded ``synthesize(SCALE_NODES)`` topology and submits two
   gang-arrival waves, waiting for every pod to bind,
2. submits one DOOMED gang (chips/pod beyond any node) into the largest
   pool and asserts the flight recorder's verdict list is truncated: at
   most ``verdict_top_k`` exact rows plus aggregated ``...and N more
   nodes: reason`` summaries, never one row per node,
3. runs a watch storm (concurrent NDJSON streams + mass relists) and pod
   churn / node kills between two monitoring-plane scrapes,
4. scrapes ``/metrics`` directly (bind-latency histogram populated, watch
   fanout counter moved, cycles/sec gauge live) AND through the PR 10
   monitoring plane (Scraper -> TSDB), asserting the new SLIs are
   queryable: ``scheduler_cycles_per_sec`` latest, windowed
   ``histogram_quantile`` over ``scheduler_bind_latency_seconds`` and the
   storm's ``apiserver_request_seconds{verb="list"}``, and
   ``workqueue_saturation`` per queue.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only; the
presubmit topology (500 nodes) keeps the whole run in tens of seconds.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.request

SEED = 11
SCALE_NODES = int(os.environ.get("SCALE_NODES", "500"))
WAVE_GANGS = int(os.environ.get("SCALE_GANGS", "6"))
VERDICT_TOP_K = 8


def _metric_value(text: str, name: str, **labels) -> float:
    """Sum of series for ``name`` whose label set includes ``labels``."""
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue  # e.g. name_bucket / name_count suffixes
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def _poll(fn, timeout: float = 30.0, interval: float = 0.1, desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def run() -> dict:
    from kubeflow_tpu.apiserver.server import make_apiserver_app
    from kubeflow_tpu.apiserver.store import Store
    from kubeflow_tpu.controllers.builtin import PodletReconciler
    from kubeflow_tpu.monitoring.scrape import Scraper, Target
    from kubeflow_tpu.monitoring.tsdb import TSDB
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.scale.loadgen import LoadGenerator
    from kubeflow_tpu.scale.topology import GangShape, synth_gangs, synthesize
    from kubeflow_tpu.scheduler import SchedulerReconciler

    topo = synthesize(SCALE_NODES, seed=SEED)
    store = Store()
    mgr = Manager(store)
    mgr.add(SchedulerReconciler(
        assembly_timeout=10.0, reservation_ttl=5.0,
        backoff_base=0.05, backoff_cap=0.5, verdict_top_k=VERDICT_TOP_K))
    mgr.add(PodletReconciler())
    app = make_apiserver_app(store)
    httpd = app.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    mgr.start()
    try:
        gen = LoadGenerator(base, topo, seed=SEED)
        registered = gen.register_nodes()
        assert registered == topo.total_nodes, (registered, topo.total_nodes)

        tsdb = TSDB()
        scraper = Scraper(tsdb, targets=[Target(job="apiserver", url=f"{base}/metrics")])

        # -- wave 1: seeded gang arrivals, all must bind ---------------------
        shapes = synth_gangs(topo, WAVE_GANGS, seed=SEED, prefix="wave1", max_size=6)
        gen.gang_wave(shapes)
        gen.wait_gangs_bound([s.name for s in shapes], timeout_s=90.0)

        up = scraper.scrape_once()  # baseline points: windowed increase needs two
        assert all(up.values()), f"monitoring scrape must reach the apiserver: {up}"

        # -- wave 2 + storm between the two scrapes --------------------------
        wave2 = synth_gangs(topo, WAVE_GANGS, seed=SEED + 1, prefix="wave2", max_size=6)
        gen.gang_wave(wave2)
        gen.wait_gangs_bound([s.name for s in wave2], timeout_s=90.0)

        storm = gen.watch_storm(streams=8, relists=24, duration_s=1.5)
        assert storm["lists"] >= 24 and storm["watch_events"] > 0, storm
        churned = gen.churn_pods(0.25)
        killed = gen.kill_nodes(max(1, topo.total_nodes // 100))

        # -- doomed gang: force verdict truncation over a big pool -----------
        big_pool = max(topo.pools, key=lambda p: p.nodes)
        assert big_pool.nodes > VERDICT_TOP_K, "need a pool larger than top_k"
        doomed = GangShape(name="doomed", size=2,
                           chips_per_pod=big_pool.chips_per_node * 4,
                           selector=big_pool.selector())
        gen.submit_gang(doomed)

        def truncated_decision():
            doc = gen._get("/debug/scheduler?gang=default/doomed&limit=64")
            hits = [d for d in doc["decisions"] if d["outcome"] == "unschedulable"]
            return hits[-1] if hits else None

        decision = _poll(truncated_decision, timeout=30.0,
                         desc="unschedulable decision for default/doomed")
        nodes = decision.get("nodes") or []
        summaries = [v for v in nodes if v.get("truncated")]
        exact = [v for v in nodes if not v.get("truncated")]
        assert summaries, f"verdicts must carry an aggregated tail: {nodes[:3]}"
        assert len(exact) <= VERDICT_TOP_K, \
            f"flight recorder kept {len(exact)} exact verdicts (top_k={VERDICT_TOP_K})"
        truncated_total = sum(v["truncated"] for v in summaries)
        assert len(exact) + truncated_total >= big_pool.nodes - 1, \
            "summary counts must cover the whole candidate pool"
        # dominant reason and message were derived from the FULL verdict
        # list before truncation — they stay exact
        assert decision.get("reason") and decision.get("message"), decision

        # -- /metrics direct: the new SLIs exist at the source ---------------
        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        bind_count = _metric_value(text, "scheduler_bind_latency_seconds_count")
        assert bind_count >= WAVE_GANGS * 2, \
            f"bind-latency histogram must cover both waves (count={bind_count})"
        cycles = _metric_value(text, "scheduler_cycles_per_sec")
        assert cycles > 0, "cycles/sec gauge must be live while reconciling"
        assert _metric_value(text, "apiserver_watch_events_sent_total") > 0
        assert _metric_value(text, "workqueue_saturation", queue="SchedulerReconciler") >= 0
        assert _metric_value(
            text, "apiserver_request_seconds_count", verb="list", resource="pods") > 0

        # -- monitoring plane: the SLIs are queryable after federation -------
        up = scraper.scrape_once()
        assert all(up.values()), f"second scrape must succeed: {up}"
        now = time.time()
        cycles_latest = tsdb.latest("scheduler_cycles_per_sec")
        assert cycles_latest, "TSDB must hold the cycles/sec gauge"
        bind_p99 = tsdb.histogram_quantile(
            "scheduler_bind_latency_seconds", 0.99, 600.0, now)
        assert bind_p99 is not None and bind_p99 >= 0.0, bind_p99
        list_p99 = tsdb.histogram_quantile(
            "apiserver_request_seconds", 0.99, 600.0, now, matchers={"verb": "list"})
        assert list_p99 is not None and list_p99 >= 0.0, \
            "storm list latency must be queryable from the TSDB"
        saturation = tsdb.latest("workqueue_saturation")
        assert any(lbl.get("queue") == "SchedulerReconciler"
                   for lbl, _ts, _v in saturation), saturation

        return {
            "ok": True,
            "nodes": topo.total_nodes,
            "pools": len(topo.pools),
            "gangs_bound": len(shapes) + len(wave2),
            "storm": storm,
            "churned": churned,
            "killed": len(killed),
            "verdicts_exact": len(exact),
            "verdicts_truncated": truncated_total,
            "bind_count": bind_count,
            "cycles_per_sec": cycles,
            "bind_p99_s": round(bind_p99, 4),
            "list_p99_s": round(list_p99, 6),
        }
    finally:
        httpd.close()
        mgr.stop()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
