"""Serving-fleet e2e: a 3-replica engine fleet driven over REAL HTTP
(ISSUE 6 acceptance criteria, CI job serving-fleet-e2e).

Boots a ModelServer hosting a tiny GPT ``GenerativeModel`` whose engine
is an ``EngineFleet`` (3 replicas, 2 slots each) on a real listener,
then:

1. **Prefix affinity** — POSTs the SAME prompt repeatedly and asserts
   ``fleet_prefix_hits_total`` > 0 on the ``/metrics`` scrape, that
   ``/debug/fleet`` shows exactly one replica holding the warm prefix,
   and that the engine gauges now carry ``replica`` labels.
2. **SLO autoscaling** — injects a synthetic TTFT breach into the SLO
   histogram, ticks a deterministic ``SLOAutoscaler``, and asserts the
   fleet scales 3 → 4 (visible over HTTP in ``/debug/fleet``), then
   scales back down once the windows go idle.
3. **Drain/handoff** — fires a burst of same-prefix requests from
   threads so pendings pile on one replica, drains that replica
   mid-burst, and asserts every HTTP response came back 200 with the
   identical greedy completion — zero dropped, zero failed — plus
   ``fleet_requeued_total`` > 0 and a ``fleet_drain_seconds`` sample.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only,
tiny config, ~tens of seconds.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request

REPLICAS = 3
MAX_REPLICAS = 4
SLOTS = 2
BURST = 8
BUDGET = 24


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read()


def _post(url: str, body: dict) -> dict:
    req = urllib.request.Request(
        url, json.dumps(body).encode(), {"content-type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


def _poll(fn, timeout: float = 30.0, interval: float = 0.01,
          desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _metric_value(text: str, name: str, **labels) -> float:
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def run() -> dict:
    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.serving.autoscaler import AutoscalerConfig, SLOAutoscaler
    from kubeflow_tpu.serving.continuous import TTFT_BUCKETS
    from kubeflow_tpu.serving.server import ModelServer, gpt_served_model

    model = gpt_served_model(name="gpt", tiny=True, max_new_tokens=BUDGET,
                             replicas=REPLICAS)
    model.max_replicas = MAX_REPLICAS
    model.slots = SLOTS
    server = ModelServer()
    server.add(model)
    fleet = model._continuous_engine()
    httpd = server.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    report: dict = {"ok": True}
    try:
        prompt = list(range(1, 9))
        url = f"{base}/v1/models/gpt:predict"

        # -- (a) prefix affinity over HTTP -----------------------------------
        reference = None
        for _ in range(6):
            out = _post(url, {"instances": [prompt]})["predictions"][0]
            if reference is None:
                reference = out
            assert out == reference, "greedy decode must be deterministic"
        text = _get(f"{base}/metrics").decode()
        hits = _metric_value(text, "fleet_prefix_hits_total")
        assert hits > 0, f"fleet_prefix_hits_total={hits}"
        assert 'serving_queue_depth{replica="' in text, \
            "engine gauges must carry the replica label"
        fleet_doc = json.loads(_get(f"{base}/debug/fleet"))
        assert fleet_doc["desired_replicas"] == REPLICAS, fleet_doc
        warm = [r for r in fleet_doc["replicas"] if r["warm_prefixes"] > 0]
        assert len(warm) == 1, \
            f"one replica must own the warm prefix, got {len(warm)}"
        report["prefix_hits"] = hits
        report["warm_replica"] = warm[0]["id"]

        # -- (b) SLO breach scales up; idle scales down ----------------------
        autoscaler = SLOAutoscaler(fleet, AutoscalerConfig(
            ttft_slo=0.5, queue_wait_slo=10.0, quantile=0.99,
            breach_ticks=2, idle_ticks=2, cooldown_ticks=1))
        autoscaler.tick()  # baseline snapshot
        ttft = METRICS.histogram("serving_ttft_seconds", buckets=TTFT_BUCKETS)
        decisions = []
        for _ in range(3):  # synthetic breach: p99 far past the 0.5s SLO
            ttft.observe(3.0, count=20)
            decisions.append(autoscaler.tick())
        assert "up" in decisions, f"breach must scale up: {decisions}"
        fleet_doc = json.loads(_get(f"{base}/debug/fleet"))
        assert fleet_doc["desired_replicas"] == REPLICAS + 1, \
            f"expected scale-up to {REPLICAS + 1}: {fleet_doc['desired_replicas']}"
        for _ in range(4):  # no traffic: windows go idle
            decisions.append(autoscaler.tick())
        assert "down" in decisions, f"idle must scale down: {decisions}"
        fleet_doc = json.loads(_get(f"{base}/debug/fleet"))
        assert fleet_doc["desired_replicas"] <= REPLICAS, fleet_doc
        text = _get(f"{base}/metrics").decode()
        assert _metric_value(text, "fleet_autoscale_total",
                             direction="up", reason="slo_breach") >= 1
        assert _metric_value(text, "fleet_autoscale_total",
                             direction="down", reason="idle") >= 1
        report["autoscale_decisions"] = [d for d in decisions if d]

        # -- (c) drain/handoff: zero dropped requests ------------------------
        results: list = [None] * BURST
        errors: list = [None] * BURST

        def fire(i: int) -> None:
            try:
                results[i] = _post(url, {"instances": [prompt]})["predictions"][0]
            except Exception as e:  # noqa: BLE001 — recorded, asserted below
                errors[i] = str(e)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(BURST)]
        for t in threads:
            t.start()

        def loaded_replica():
            for h in fleet.live_handles():
                if METRICS.value("serving_queue_depth",
                                 replica=h.gauge_id) >= 2:
                    return h
            return None

        victim = _poll(loaded_replica, timeout=30.0,
                       desc="a replica with queued pendings")
        requeued = fleet.drain_replica(victim.id, reason="e2e_drain")
        for t in threads:
            t.join(timeout=120)
        assert all(e is None for e in errors), f"failed requests: {errors}"
        assert all(r == reference for r in results), \
            "every drained/re-queued request must return the exact greedy completion"
        text = _get(f"{base}/metrics").decode()
        assert requeued > 0, "the drain must have handed off pending requests"
        assert _metric_value(text, "fleet_requeued_total") >= requeued
        assert _metric_value(text, "fleet_drain_seconds_count") >= 1
        report["drained_replica"] = victim.gauge_id
        report["requeued"] = requeued
        report["burst_ok"] = len(results)
        return report
    finally:
        httpd.close()
        server.close()
        model.close()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
