"""In-process e2e cluster: platform + fake TPU node pool + HTTP services.

The deploy/wait utility layer of the harness (the analog of
testing/deploy_utils.py:25-80 namespace-per-run fixtures,
testing/wait_for_deployment.py, and testing/gcp_util.py readiness polls).
Everything runs over real localhost HTTP so the drivers exercise the same
surfaces a browser or CI job would.
"""

from __future__ import annotations

import json
import time
import urllib.request
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubeflow_tpu.controllers.builtin import make_tpu_node
from kubeflow_tpu.platform import build_platform
from kubeflow_tpu.runtime.manager import Reconciler
from kubeflow_tpu.services.jupyter import make_jupyter_app
from kubeflow_tpu.services.kfam import make_kfam_app
from kubeflow_tpu.web.auth import AuthConfig

#: default fake node pool: one v5e 2x4 slice (8 chips = 2 hosts x 4 chips)
#: plus a spare single-host 2x2 — enough for multi-host spawn + an HPO trial.
DEFAULT_NODES: List[Tuple[str, str, int, int]] = [
    # (generation, topology label, chips per node, node count)
    ("v5e", "2x4", 4, 2),
    ("v5e", "2x2", 4, 1),
]


def free_port() -> int:
    """Pick a free TCP port so concurrent runs (pytest-xdist, parallel CI
    jobs) each get their own listener instead of colliding."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def unique_namespace(prefix: str = "e2e") -> str:
    """Namespace-per-run isolation (deploy_utils.py:25-43 pattern)."""
    return f"{prefix}-{uuid.uuid4().hex[:8]}"


def wait_for_condition(
    fn: Callable[[], Any],
    timeout: float = 30.0,
    interval: float = 0.1,
    desc: str = "condition",
) -> Any:
    """Poll fn() until it returns truthy — the katib e2e wait loop
    (testing/katib_studyjob_test.py:128-193: poll CR status under a
    deadline, raise on timeout). Returns fn()'s final value."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        last = fn()
        if last:
            return last
        time.sleep(interval)
    # Final check at/after the deadline: a condition that became true during
    # the last poll interval is a pass, not a flake.
    last = fn()
    if last:
        return last
    raise TimeoutError(f"timed out after {timeout}s waiting for {desc} (last={last!r})")


def http_json(
    method: str,
    url: str,
    body: Optional[Dict[str, Any]] = None,
    headers: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("content-type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=10) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


def csrf_headers(base_url: str, identity: Dict[str, str]) -> Dict[str, str]:
    """Fetch the double-submit CSRF cookie the way a browser would
    (crud_backend csrf.py: cookie issued on GET, echoed in X-XSRF-TOKEN)."""
    req = urllib.request.Request(base_url + "/api/config")
    for k, v in identity.items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=10) as resp:
        cookies = resp.headers.get_all("Set-Cookie") or []
    token = ""
    for c in cookies:
        if c.startswith("XSRF-TOKEN="):
            token = c.split(";", 1)[0].split("=", 1)[1]
    if not token:
        raise RuntimeError(f"no XSRF-TOKEN cookie from {base_url}/api/config")
    return {**identity, "cookie": f"XSRF-TOKEN={token}", "x-xsrf-token": token}


class E2ECluster:
    """One hermetic 'cluster': control plane + fake TPU nodes + web services.

    Usage:
        with E2ECluster() as cluster:
            ns = cluster.create_profile("alice@example.com")
            ...
    """

    def __init__(
        self,
        nodes: Optional[List[Tuple[str, str, int, int]]] = None,
        trial_runner: Optional[Reconciler] = None,
        cluster_admins: Tuple[str, ...] = ("admin@example.com",),
    ):
        self.mgr = build_platform(trial_runner=trial_runner)
        self.client = self.mgr.client
        self.auth = AuthConfig(cluster_admins=list(cluster_admins))
        self._servers: List[Any] = []
        node_specs = DEFAULT_NODES if nodes is None else nodes
        for generation, topo, chips, count in node_specs:
            for i in range(count):
                self.client.create(
                    make_tpu_node(f"tpu-{generation}-{topo}-{i}", generation, topo, chips)
                )

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "E2ECluster":
        self.mgr.start()
        return self

    def stop(self) -> None:
        try:
            for server in self._servers:
                try:
                    server.close()
                except Exception:
                    pass  # a half-torn-down listener must not block shutdown
        finally:
            self._servers.clear()
            self.mgr.stop()

    def __enter__(self) -> "E2ECluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- services ------------------------------------------------------------
    def serve_jupyter(self) -> str:
        server = make_jupyter_app(self.client, auth=self.auth).serve(0)
        self._servers.append(server)
        return f"http://127.0.0.1:{server.port}"

    def serve_kfam(self) -> str:
        server = make_kfam_app(self.client, auth=self.auth).serve(0)
        self._servers.append(server)
        return f"http://127.0.0.1:{server.port}"

    # -- fixtures ------------------------------------------------------------
    def create_profile(self, owner: str, name: Optional[str] = None, timeout: float = 30.0) -> str:
        """Create a Profile CR and wait until its namespace + RBAC exist —
        the per-run fixture the reference builds with deploy_utils +
        profiles_test assertions (py/kubeflow/kubeflow/ci/profiles_test.py)."""
        ns = name or unique_namespace()
        self.client.create(
            {
                "apiVersion": "kubeflow.org/v1",
                "kind": "Profile",
                "metadata": {"name": ns},
                "spec": {"owner": {"kind": "User", "name": owner}},
            }
        )
        wait_for_condition(
            lambda: self.client.get_opt("v1", "Namespace", ns) is not None
            and any(
                (rb.get("roleRef") or {}).get("name") == "kubeflow-admin"
                for rb in self.client.list("rbac.authorization.k8s.io/v1", "RoleBinding", ns)
            ),
            timeout=timeout,
            desc=f"profile namespace {ns} ready",
        )
        return ns

    def wait_idle(self, timeout: float = 30.0) -> None:
        self.mgr.wait_idle(timeout=timeout)
