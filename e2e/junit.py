"""junit XML results — the reference ships these to gubernator
(testing/test_tf_serving.py:139-143 builds TestCase objects and calls
test_util.create_junit_xml_file)."""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import List, Optional
from xml.sax.saxutils import escape, quoteattr


@dataclass
class TestCaseResult:
    __test__ = False  # not a pytest collectable

    class_name: str
    name: str
    time_seconds: float = 0.0
    failure: Optional[str] = None  # failure text, None = pass

    @property
    def passed(self) -> bool:
        return self.failure is None


@dataclass
class TestSuite:
    __test__ = False  # not a pytest collectable

    name: str
    cases: List[TestCaseResult] = field(default_factory=list)

    def run(self, class_name: str, name: str, fn) -> TestCaseResult:
        """Execute fn() as one junit case, recording time and failure."""
        t0 = time.perf_counter()
        failure = None
        try:
            fn()
        except Exception as e:  # record, don't raise — suites report all cases
            failure = f"{type(e).__name__}: {e}"
        case = TestCaseResult(class_name, name, time.perf_counter() - t0, failure)
        self.cases.append(case)
        return case

    @property
    def passed(self) -> bool:
        return all(c.passed for c in self.cases)


def junit_xml(suite: TestSuite) -> str:
    lines = [
        '<?xml version="1.0" encoding="utf-8"?>',
        f"<testsuite name={quoteattr(suite.name)} tests=\"{len(suite.cases)}\" "
        f"failures=\"{sum(1 for c in suite.cases if not c.passed)}\">",
    ]
    for c in suite.cases:
        open_tag = (
            f"  <testcase classname={quoteattr(c.class_name)} "
            f"name={quoteattr(c.name)} time=\"{c.time_seconds:.3f}\""
        )
        if c.passed:
            lines.append(open_tag + "/>")
        else:
            lines.append(open_tag + ">")
            lines.append(f"    <failure>{escape(c.failure or '')}</failure>")
            lines.append("  </testcase>")
    lines.append("</testsuite>")
    return "\n".join(lines) + "\n"


def write_junit(suite: TestSuite, path: str) -> None:
    with open(path, "w") as f:
        f.write(junit_xml(suite))


def run_driver(
    suite_name: str,
    class_name: str,
    case_name,
    make_case,
    argv=None,
    add_args=None,
    default_junit: str = "junit.xml",
) -> int:
    """Shared driver entry point: argparse (--junit + driver extras), run the
    flow as one junit case, write XML, print PASS/FAIL, return exit code.

    ``case_name`` may be a callable(args) for parameterized names;
    ``make_case(args)`` returns the zero-arg flow to execute;
    ``add_args(parser)`` registers driver-specific flags.
    """
    import argparse

    parser = argparse.ArgumentParser()
    if add_args is not None:
        add_args(parser)
    parser.add_argument("--junit", default=default_junit)
    args = parser.parse_args(argv)

    suite = TestSuite(suite_name)
    name = case_name(args) if callable(case_name) else case_name
    case = suite.run(class_name, name, make_case(args))
    write_junit(suite, args.junit)
    print(("PASS" if case.passed else f"FAIL: {case.failure}") + f" ({case.time_seconds:.1f}s)")
    return 0 if suite.passed else 1
