"""Disaggregated serving e2e: prefill/decode pools, quantized-KV
handoff, and two multiplexed models driven over REAL HTTP (ISSUE 18
acceptance criteria, CI job disagg-serving-e2e).

Boots a ModelServer whose ``GenerativeModel`` runs an
``EngineFleet(pools={"prefill": 1, "decode": 2})`` multiplexing two
models ("alpha" interactive, "beta" batch) with an int8 KV arena, then:

1. **Greedy parity per model, moved == never-moved** — HTTP completions
   for both models are bit-identical to a unified single-engine int8
   oracle that never exported anything: every request prefilled on one
   replica, shipped over the KV wire, and decoded on another, adopting
   the exporter's quantized bytes verbatim. (bf16-vs-int8 tolerance is
   the unit suites' contract; the wire's contract is that moving the KV
   changes NOTHING.)
2. **Handoff counters live** — ``serving_kv_handoff_total`` and
   ``serving_kv_import_total`` both advanced, and advanced TOGETHER
   (every exported frame was adopted; nothing leaked in flight), with
   ``serving_kv_handoff_bytes``/``_seconds`` histograms populated.
3. **Chatty TTFT unharmed by a long-prefill burst** — with a long
   prompt chunk-prefilling on the prefill specialist, chatty requests'
   first tokens still beat the long request's own first token: the
   compute-bound phase never occupies a decode slot.
4. **int8 halves KV bytes** — two accounting engines with identical
   arenas (``serving_kv_blocks_free`` agrees on capacity) differ ~2x in
   arena HBM bytes: KV slots per HBM byte is ~doubled (head_dim 64:
   2D/(D+4) = 1.88x; the f32 scale column is the deficit from 2.0).
5. **Zero drops through a decode-pool drain** — a decode replica is
   drained mid-burst; its in-flight imports re-import into the
   surviving decode replica and every request still returns the exact
   oracle completion.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only,
tiny config, ~a few minutes (six engines compile).
"""

from __future__ import annotations

import json
import sys
import threading
import urllib.error
import urllib.request

POOLS = {"prefill": 1, "decode": 2}
SLOTS = 4
BUDGET = 16
PREFILL_CHUNK = 32
LONG_PROMPT = 160
LONG_BURST = 4
CHATTY = 4


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read()


def _post(url: str, body: dict, timeout: float = 300.0) -> tuple:
    req = urllib.request.Request(
        url, json.dumps(body).encode(), {"content-type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = {"raw": raw.decode(errors="replace")}
        return e.code, parsed


def _metric_value(text: str, name: str, **labels) -> float:
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from kubeflow_tpu.models.gpt import GptConfig, GptLM
    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.serving.continuous import ContinuousBatcher
    from kubeflow_tpu.serving.server import GenerativeModel, ModelServer

    cfg = GptConfig(vocab_size=512, d_model=64, n_layers=2, n_heads=4,
                    d_ff=128, max_seq=256)
    params = {
        mid: GptLM(cfg).init(jax.random.PRNGKey(seed),
                             jnp.zeros((1, 8), jnp.int32))["params"]
        for mid, seed in (("alpha", 0), ("beta", 1))}

    rng = np.random.default_rng(18)
    long_prompt = rng.integers(1, cfg.vocab_size, size=LONG_PROMPT).tolist()
    chatty_prompts = [rng.integers(1, cfg.vocab_size, size=8).tolist()
                      for _ in range(CHATTY)]

    # never-moved oracles: unified engines with the SAME int8 arena — the
    # wire's contract is byte-identical output moved vs never-moved
    oracle_engines = {
        mid: ContinuousBatcher(cfg, p, slots=SLOTS,
                               prefill_chunk=PREFILL_CHUNK, kv_dtype="int8",
                               engine_id=f"nm-{mid}")
        for mid, p in params.items()}
    _oracle_cache: dict = {}

    def oracle(mid: str, prompt: list) -> list:
        """Full sequence (prompt + completion), matching the HTTP shape."""
        key = (mid, tuple(prompt))
        if key not in _oracle_cache:
            toks = oracle_engines[mid].submit(
                np.asarray(prompt, np.int32), BUDGET).result(timeout=600)
            _oracle_cache[key] = list(prompt) + toks
        return _oracle_cache[key]

    model = GenerativeModel(
        name="gpt", apply_fn=None, params=params["alpha"], cfg=cfg,
        max_new_tokens=BUDGET, temperature=0.0, slots=SLOTS,
        prefill_chunk=PREFILL_CHUNK, kv_dtype="int8",
        # max_replicas bounds every pool: without headroom the decode
        # pool would be clamped to 1 per model and the drain phase would
        # leave alpha with no decode replica at all
        max_replicas=4,
        pools=dict(POOLS),
        mux_models={mid: (cfg, p) for mid, p in params.items()},
        model_slo={"alpha": "interactive", "beta": "batch"})
    server = ModelServer()
    server.add(model)
    httpd = server.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    url = f"{base}/v1/models/gpt:predict"
    report: dict = {"ok": True}
    try:
        fleet = model._continuous_engine()
        assert fleet.pool_size("decode") == POOLS["decode"], \
            f"decode pool clamped: {fleet.pool_size('decode')}"
        # -- (0) warm every (pool, model) engine's compile cache ------------
        warm = []
        for mid in params:
            warm.append(fleet.submit(np.asarray(chatty_prompts[0], np.int32),
                                     BUDGET, model=mid))
            warm.append(fleet.submit(np.asarray(long_prompt, np.int32),
                                     BUDGET, model=mid))
        for w in warm:
            w.result(timeout=600)

        # -- (1) per-model greedy parity through the quantized wire ---------
        handoffs0 = _metric_value(_get(f"{base}/metrics").decode(),
                                  "serving_kv_handoff_total")
        n_http = 0
        for mid in params:
            for p in chatty_prompts:
                status, out = _post(url, {"instances": [p], "model": mid})
                assert status == 200, f"{mid} got {status}: {out}"
                assert out["predictions"][0] == oracle(mid, p), \
                    f"model {mid}: moved+quantized decode diverged from its oracle"
                n_http += 1
        # models must not alias: same prompt, different completions
        assert (oracle("alpha", chatty_prompts[0])
                != oracle("beta", chatty_prompts[0])), \
            "sanity: the two models must disagree for isolation to be tested"
        report["parity"] = {"requests": n_http, "models": sorted(params)}

        # -- (2) every exported KV frame was adopted ------------------------
        text = _get(f"{base}/metrics").decode()
        handoffs = _metric_value(text, "serving_kv_handoff_total")
        imports = _metric_value(text, "serving_kv_import_total")
        assert handoffs - handoffs0 >= n_http, \
            f"expected >= {n_http} handoffs, counter moved {handoffs - handoffs0}"
        assert imports == handoffs, \
            f"handoffs {handoffs} != imports {imports}: a frame leaked in flight"
        hb = METRICS.histogram_counts("serving_kv_handoff_bytes")
        hs = METRICS.histogram_counts("serving_kv_handoff_seconds")
        assert hb is not None and hb[2] == int(handoffs)
        assert hs is not None and hs[2] == int(handoffs)
        report["handoff"] = {"count": handoffs,
                             "pool_replicas": {
                                 "prefill": fleet.pool_size("prefill"),
                                 "decode": fleet.pool_size("decode")}}

        # -- (3) chatty TTFT survives a long-prefill burst ------------------
        # The disaggregation contract: long prompts chunk-prefill ONE at a
        # time on the prefill specialist while short prompts keep batching
        # through every admission cycle, and decode slots are claimed only
        # at handoff — so chatty requests submitted behind a BURST of long
        # prefills jump the queue instead of FIFO-waiting it out. A single
        # long prompt at this model size prefills in tens of milliseconds
        # (handoff overhead would dominate the comparison); the burst is
        # what makes the ordering observable.
        burst = [long_prompt] + [
            rng.integers(1, cfg.vocab_size, size=LONG_PROMPT).tolist()
            for _ in range(LONG_BURST - 1)]
        burst_refs = [oracle("alpha", p) for p in burst]
        long_reqs = [fleet.submit(np.asarray(p, np.int32), BUDGET,
                                  model="alpha") for p in burst]
        chatty_reqs = [fleet.submit(np.asarray(p, np.int32), BUDGET,
                                    model="alpha")
                       for p in chatty_prompts[:3]]
        for r, ref in zip(long_reqs, burst_refs):
            assert r.result(timeout=600) == ref[LONG_PROMPT:]
        for i, r in enumerate(chatty_reqs):
            assert r.result(timeout=600) == \
                oracle("alpha", chatty_prompts[i])[8:]
        last_long_first = max(r.first_token_at for r in long_reqs)
        burst_span = last_long_first - long_reqs[0].submit_at
        chatty_ttfts = [r.first_token_at - r.submit_at for r in chatty_reqs]
        for i, r in enumerate(chatty_reqs):
            assert r.first_token_at < last_long_first, \
                f"chatty[{i}] TTFT {chatty_ttfts[i]:.3f}s — first token " \
                f"arrived after the whole {LONG_BURST}-long burst " \
                f"({burst_span:.3f}s): shorts are FIFO-stuck behind prefill"
        report["ttft"] = {"long_burst_span_s": round(burst_span, 3),
                          "chatty_max_s": round(max(chatty_ttfts), 3)}

        # -- (4) int8 arena: ~2x KV slots per HBM byte ----------------------
        acct_cfg = GptConfig(vocab_size=64, d_model=64, n_layers=1,
                             n_heads=1, d_ff=64, max_seq=128)
        acct_params = GptLM(acct_cfg).init(
            jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32))["params"]
        arena_bytes, blocks = {}, {}
        for dt in ("bf16", "int8"):
            eng = ContinuousBatcher(acct_cfg, acct_params, slots=2,
                                    kv_dtype=dt, engine_id=f"acct-{dt}")
            try:
                blocks[dt] = _metric_value(
                    _get(f"{base}/metrics").decode(),
                    "serving_kv_blocks_free", replica=f"acct-{dt}")
                arena_bytes[dt] = sum(
                    leaf.nbytes for blk in eng.cache.values()
                    for name, leaf in blk["attention"].items()
                    if name != "cursors")
            finally:
                eng.close()
        assert blocks["bf16"] == blocks["int8"] > 0, \
            f"capacity parity broken: {blocks}"
        ratio = arena_bytes["bf16"] / arena_bytes["int8"]
        assert ratio >= 1.8, \
            f"int8 arena saves only {ratio:.2f}x (want ~2x): {arena_bytes}"
        report["int8_hbm"] = {"blocks": blocks["int8"],
                              "bf16_bytes": arena_bytes["bf16"],
                              "int8_bytes": arena_bytes["int8"],
                              "slots_per_byte_gain": round(ratio, 3)}

        # -- (5) decode-pool drain drops nothing ----------------------------
        outs: list = [None] * 6

        def client(i: int) -> None:
            mid = "alpha" if i % 2 == 0 else "beta"
            p = chatty_prompts[i % CHATTY]
            outs[i] = (mid, p, _post(url, {"instances": [p], "model": mid}))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        victim = next(h for h in fleet.live_handles()
                      if h.role == "decode" and h.model_id == "alpha")
        fleet.drain_replica(victim.id, reason="e2e")
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), "client threads hung"
        for i, (mid, p, (status, out)) in enumerate(outs):
            assert status == 200, f"drain burst [{i}] got {status}: {out}"
            assert out["predictions"][0] == oracle(mid, p), \
                f"drain burst [{i}] diverged — a request was dropped or moved wrong"
        assert not any(h.id == victim.id for h in fleet.live_handles()), \
            "drained decode replica must leave the fleet"
        report["drain"] = {"requests": len(outs),
                           "decode_pool_after": fleet.pool_size("decode")}
        return report
    finally:
        for eng in oracle_engines.values():
            eng.close()
        httpd.close()
        server.close()
        model.close()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
