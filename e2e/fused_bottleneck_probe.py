"""Fused-bottleneck kernel evidence: build, verify, measure (VERDICT r4 #1).

Round 4's conv decomposition named one remaining ResNet lever: a Pallas
kernel fusing the whole bottleneck (1x1 -> 3x3 -> 1x1 + residual) so the
256-channel activations never touch HBM, estimated "+8-10 MFU points".
This probe is the measured answer (run: ``python -m e2e.fused_bottleneck_probe``):

1. ``fused``   — the real kernel (ops/fused_bottleneck.py, parity-tested)
   at stage-1 shapes, one image per grid step, auto-pipelined.
2. ``xla``     — the XLA composite of the same math (frozen norm), the
   thing the kernel must beat.
3. ``copy_*``  — pure-streaming probes that pin the mechanism: Pallas
   block-pipelined HBM streaming vs XLA's own elementwise streaming, plus
   a hand-rolled double-buffered DMA kernel (the fastest Pallas can go).

Round-5 result on the tunneled v5e chip (full table in BASELINE.md):
    xla composite        3.37 ms   33.5 TF/s   (HBM-bound at ~425 GB/s)
    fused pallas         3.90 ms   28.6 TF/s   (HBM-bound at ~199 GB/s)
    pallas copy (auto)   199 GB/s   — block shape/size invariant
    pallas copy (DMA)    283 GB/s   — manual double buffering
    xla copy             330-425 GB/s
The fused kernel moves 1.9x less HBM data and still loses: on this
backend Pallas streams HBM at ~0.5x (auto) / ~0.7x (manual DMA) of XLA's
rate, which cancels the entire fusion saving. Best case (manual DMA,
perfect overlap) is ~1.15x on the fwd of the 13 identity-shortcut blocks
~= +1 MFU point on the full step — not the projected +8-10. The lever is
refuted at kernel level; the flash kernel is unaffected because its
arithmetic intensity makes streaming rate irrelevant.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from e2e.ceiling import CHAIN, _timed

N, HW, CIN, CMID = 256, 56, 256, 64


def _inputs():
    rng = np.random.RandomState(0)
    x0 = jnp.asarray(rng.randn(N, HW, HW, CIN), jnp.bfloat16) * 0.3
    w1 = jnp.asarray(rng.randn(CIN, CMID) * 0.05, jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(3, 3, CMID, CMID) * 0.05, jnp.bfloat16)
    w3 = jnp.asarray(rng.randn(CMID, CIN) * 0.05, jnp.bfloat16)
    s1, b1 = jnp.ones(CMID), jnp.zeros(CMID) + 0.01
    s2, b2 = jnp.ones(CMID) * 1.1, jnp.zeros(CMID) - 0.01
    s3, b3 = jnp.ones(CIN) * 0.9, jnp.zeros(CIN)
    return x0, (w1, s1, b1, w2, s2, b2, w3, s3, b3)


def _bench_block(fn, x0, weights, label) -> Dict[str, Any]:
    flops = 2.0 * N * HW * HW * (CIN * CMID + 9 * CMID * CMID + CMID * CIN)

    @jax.jit
    def run(x):
        def body(x, _):
            for _ in range(CHAIN):
                y = fn(x, *weights)
                x = (y * jnp.bfloat16(0.97)).astype(jnp.bfloat16)
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=8)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x0,), 8 * CHAIN)
    return {"probe": label, "ms_per_pass": round(dt * 1e3, 3),
            "tflops": round(flops / dt / 1e12, 1)}


def _bench_copy(fn, x0, label) -> Dict[str, Any]:
    nbytes = x0.size * 2

    @jax.jit
    def run(x):
        def body(x, _):
            for _ in range(4):
                x = fn(x)
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=8)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x0,), 32)
    return {"probe": label, "ms_per_pass": round(dt * 1e3, 3),
            "gbps_rw": round(2 * nbytes / dt / 1e9)}


def _pallas_copy(shape, block):
    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * jnp.bfloat16(0.97)

    n_blocks = shape[0] // block[0]
    return pl.pallas_call(
        kern, grid=(n_blocks,),
        in_specs=[pl.BlockSpec(block, lambda i: (i,) + (0,) * (len(block) - 1))],
        out_specs=pl.BlockSpec(block, lambda i: (i,) + (0,) * (len(block) - 1)),
        out_shape=jax.ShapeDtypeStruct(shape, jnp.bfloat16), interpret=False)


def _manual_dma_copy(m, c, bm=4096):
    nb = m // bm

    def kern(x_hbm, o_hbm, buf, obuf, in_sems, out_sems):
        def get(i, slot):
            return pltpu.make_async_copy(
                x_hbm.at[pl.ds(i * bm, bm), :], buf.at[slot], in_sems.at[slot])

        def put(i, slot):
            return pltpu.make_async_copy(
                obuf.at[slot], o_hbm.at[pl.ds(i * bm, bm), :], out_sems.at[slot])

        get(0, 0).start()

        def body(i, _):
            slot = jax.lax.rem(i, 2)
            nxt = jax.lax.rem(i + 1, 2)

            @pl.when(i + 1 < nb)
            def _():
                get(i + 1, nxt).start()

            get(i, slot).wait()

            @pl.when(i >= 2)
            def _():
                put(i - 2, slot).wait()

            obuf[slot] = buf[slot] * jnp.bfloat16(0.97)
            put(i, slot).start()
            return 0

        jax.lax.fori_loop(0, nb, body, 0)
        put(nb - 2, jax.lax.rem(nb - 2, 2)).wait()
        put(nb - 1, jax.lax.rem(nb - 1, 2)).wait()

    return pl.pallas_call(
        kern,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((m, c), jnp.bfloat16),
        scratch_shapes=[
            pltpu.VMEM((2, bm, c), jnp.bfloat16),
            pltpu.VMEM((2, bm, c), jnp.bfloat16),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=False,
    )


def main() -> int:
    from kubeflow_tpu.ops.fused_bottleneck import fused_bottleneck, reference_bottleneck

    rows: List[Dict[str, Any]] = []
    x0, weights = _inputs()
    rows.append(_bench_block(reference_bottleneck, x0, weights, "xla_composite"))
    rows.append(_bench_block(
        functools.partial(fused_bottleneck, interpret=False), x0, weights,
        "fused_pallas"))

    flat = x0.reshape(N * HW * HW, CIN)
    rows.append(_bench_copy(lambda x: x * jnp.bfloat16(0.97), flat, "xla_copy_2d"))
    rows.append(_bench_copy(_pallas_copy(flat.shape, (3136, CIN)), flat,
                            "pallas_copy_auto_2d"))
    rows.append(_bench_copy(_pallas_copy(x0.shape, (1, HW, HW, CIN)), x0,
                            "pallas_copy_auto_4d"))
    rows.append(_bench_copy(_manual_dma_copy(N * HW * HW, CIN), flat,
                            "pallas_copy_manual_dma"))

    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps({"metric": "fused_bottleneck_probe", "rows": rows}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
