"""Serving overload-protection e2e: a 3-replica fleet driven over REAL
HTTP past saturation (ISSUE 9 acceptance criteria, CI job
serving-overload-e2e).

Boots a ModelServer hosting a tiny GPT ``GenerativeModel`` whose engine
is an ``EngineFleet`` (3 replicas, 2 slots each, bounded admission
queues) on a real listener, then:

1. **Determinism baseline** — same prompt POSTed repeatedly returns the
   identical greedy completion.
2. **Deadline fast-fail** — an already-expired ``X-Request-Deadline-Ms``
   comes back 504 in well under the decode time; nothing occupies a slot.
3. **Priority shedding under flood** — ~2x the fleet's batch-admissible
   capacity in concurrent ``priority=batch`` POSTs plus a trickle of
   interactive POSTs: batch sheds with 503 + ``Retry-After`` while every
   interactive request is served (``serving_shed_total{priority=
   "interactive"}`` stays 0), and every client thread returns.
4. **Client abandonment** — chaos ``client_abandon`` cancels a burst
   mid-decode on slowed replicas; ``serving_cancelled_total`` counts it
   and the freed slots are reclaimed.
5. **Breaker cycle** — chaos ``slow_replica`` on one replica plus short
   per-request deadlines drives consecutive expiries until that
   replica's breaker OPENS (``fleet_breaker_state`` = 1 on /metrics);
   traffic keeps flowing 200 through the survivors; once the fault
   lifts, a probe request re-CLOSES the breaker (gauge back to 0).
6. **Crash survival** — chaos ``crash_replica_mid_decode`` poisons a
   replica; a follow-up burst still returns all-200 through the fleet.
7. **Reclamation** — every queue depth and active-slot gauge on live
   replicas drains back to zero: no expired, abandoned, or shed request
   leaks a slot, and zero client threads hang.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only,
tiny config, ~tens of seconds.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.error
import urllib.request

REPLICAS = 3
SLOTS = 2
BUDGET = 24
#: engine-side admission cap (per replica) and interactive reserve
MAX_PENDING = 8
ENGINE_RESERVE = 0.5
#: router-side queue-depth cap and interactive reserve: batch saturates
#: at depth 2, interactive at 8
ROUTER_DEPTH = 8
ROUTER_RESERVE = 0.75
#: batch-admissible concurrency = slots + engine batch cap, per replica
BATCH_CAPACITY = REPLICAS * (SLOTS + int(MAX_PENDING * (1 - ENGINE_RESERVE)))
#: flood at ~2.2x that capacity
FLOOD = 40
INTERACTIVE_CLIENTS = 4


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=60) as resp:
        return resp.read()


def _post(url: str, body: dict, headers: dict = None,
          timeout: float = 120.0) -> tuple:
    """POST returning ``(status, headers, parsed_body)`` — 4xx/5xx are
    observations here, not exceptions (the whole point is asserting on
    503/504 semantics)."""
    hdrs = {"content-type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, json.dumps(body).encode(), hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, dict(resp.headers), json.loads(resp.read())
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            parsed = json.loads(raw)
        except ValueError:
            parsed = {"raw": raw.decode(errors="replace")}
        return e.code, dict(e.headers), parsed


def _poll(fn, timeout: float = 30.0, interval: float = 0.02,
          desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _metric_value(text: str, name: str, **labels) -> float:
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def run() -> dict:
    from kubeflow_tpu.runtime.chaos import ChaosMonkey, ChaosSchedule, Fault
    from kubeflow_tpu.serving.continuous import ContinuousBatcher
    from kubeflow_tpu.serving.fleet import EngineFleet, ReplicaBreaker
    from kubeflow_tpu.serving.router import PrefixRouter
    from kubeflow_tpu.serving.server import ModelServer, gpt_served_model

    model = gpt_served_model(name="gpt", tiny=True, max_new_tokens=BUDGET)

    def engine_factory(engine_id: str):
        return ContinuousBatcher(model.cfg, model.params, slots=SLOTS,
                                 chunk=8, pipeline=2, engine_id=engine_id,
                                 max_pending=MAX_PENDING,
                                 interactive_reserve=ENGINE_RESERVE)

    fleet = EngineFleet(
        replicas=REPLICAS, max_replicas=REPLICAS, name="gpt",
        engine_factory=engine_factory,
        router=PrefixRouter(max_queue_depth=ROUTER_DEPTH,
                            interactive_reserve=ROUTER_RESERVE),
        breaker_factory=lambda: ReplicaBreaker(failure_threshold=2,
                                               open_s=2.0))
    model._engine = fleet  # GenerativeModel serves through this fleet
    server = ModelServer()
    server.add(model)
    httpd = server.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"
    url = f"{base}/v1/models/gpt:predict"
    monkey = ChaosMonkey(None, ChaosSchedule([]), fleet=fleet)
    report: dict = {"ok": True,
                    "saturation_factor": round(FLOOD / BATCH_CAPACITY, 2)}
    try:
        # -- (1) determinism baseline ---------------------------------------
        warm = list(range(1, 9))
        reference = None
        for _ in range(4):
            status, _h, out = _post(url, {"instances": [warm]})
            assert status == 200, f"warmup got {status}: {out}"
            if reference is None:
                reference = out["predictions"][0]
            assert out["predictions"][0] == reference, \
                "greedy decode must be deterministic"

        # -- (2) already-expired deadline 504s fast -------------------------
        t0 = time.monotonic()
        status, _h, out = _post(url, {"instances": [warm]},
                                headers={"X-Request-Deadline-Ms": "0"})
        elapsed = time.monotonic() - t0
        assert status == 504, f"expired deadline got {status}: {out}"
        assert elapsed < 5.0, f"DOA deadline took {elapsed:.1f}s to fail"
        report["doa_504_s"] = round(elapsed, 3)

        # -- (3) mixed-priority flood at ~2.2x batch capacity ---------------
        results: list = [None] * (FLOOD + INTERACTIVE_CLIENTS * 2)

        def batch_client(i: int) -> None:
            body = {"instances": [[10 + i] * 8], "priority": "batch",
                    "timeout_ms": 60000}
            results[i] = _post(url, body)

        def interactive_client(j: int) -> None:
            for k in range(2):
                body = {"instances": [[200 + j] * 8],
                        "priority": "interactive", "timeout_ms": 120000}
                results[FLOOD + j * 2 + k] = _post(url, body)

        threads = [threading.Thread(target=batch_client, args=(i,))
                   for i in range(FLOOD)]
        for j in range(INTERACTIVE_CLIENTS):
            threads.append(
                threading.Thread(target=interactive_client, args=(j,)))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        hung = [t.name for t in threads if t.is_alive()]
        assert not hung, f"hung client threads: {hung}"
        assert all(r is not None for r in results), "a client died mid-POST"
        batch_statuses = [r[0] for r in results[:FLOOD]]
        inter_statuses = [r[0] for r in results[FLOOD:]]
        shed = [r for r in results[:FLOOD] if r[0] == 503]
        assert shed, f"flood at 2x capacity must shed batch: {batch_statuses}"
        for _s, hdrs, _b in shed:
            retry_after = hdrs.get("Retry-After")
            assert retry_after is not None and int(retry_after) >= 1, \
                f"503 must carry Retry-After, got headers {hdrs}"
        assert all(s == 200 for s in inter_statuses), \
            f"interactive must never shed while batch does: {inter_statuses}"
        text = _get(f"{base}/metrics").decode()
        assert _metric_value(text, "serving_shed_total", priority="batch") > 0
        assert _metric_value(text, "serving_shed_total",
                             priority="interactive") == 0
        report["flood"] = {"batch_200": batch_statuses.count(200),
                           "batch_503": batch_statuses.count(503),
                           "interactive_200": inter_statuses.count(200)}

        # -- (4) client abandonment frees slots -----------------------------
        for h in fleet.live_handles():  # slow everything so the burst is
            monkey.inject(Fault(at=0.0, kind="slow_replica",  # still in flight
                                target=h.gauge_id, param=0.5, duration=4.0))
        aband: list = [None] * 4

        def abandoned_client(i: int) -> None:
            aband[i] = _post(url, {"instances": [[60 + i] * 8],
                                   "priority": "batch",
                                   "timeout_ms": 60000})

        ats = [threading.Thread(target=abandoned_client, args=(i,))
               for i in range(len(aband))]
        for t in ats:
            t.start()
        time.sleep(0.4)  # let them admit and start decoding
        monkey.inject(Fault(at=0.0, kind="client_abandon", param=len(aband)))
        for t in ats:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in ats), "abandoned clients hung"
        _poll(lambda: all(h.engine.step_delay_s == 0.0
                          for h in fleet.live_handles()),
              timeout=15.0, desc="slow_replica faults to expire")
        text = _get(f"{base}/metrics").decode()
        cancelled = _metric_value(text, "serving_cancelled_total")
        assert cancelled >= 1, f"serving_cancelled_total={cancelled}"
        report["abandoned"] = {"cancelled": cancelled,
                               "statuses": [a[0] for a in aband]}

        # -- (5) breaker opens on a slowed replica, then re-closes ----------
        victim = fleet.live_handles()[0].gauge_id
        monkey.inject(Fault(at=0.0, kind="slow_replica", target=victim,
                            param=1.0, duration=8.0))
        pd = [77] * 8  # fresh prompt: ties route it to the victim first,
        opened = False  # then prefix affinity keeps it there
        deadline_statuses = []
        for _ in range(6):
            status, _h, _b = _post(url, {"instances": [pd]},
                                   headers={"X-Request-Deadline-Ms": "700"})
            deadline_statuses.append(status)
            state = _metric_value(_get(f"{base}/metrics").decode(),
                                  "fleet_breaker_state", replica=victim)
            if state == 1.0:
                opened = True
                break
        assert opened, \
            f"breaker never opened; deadline statuses={deadline_statuses}"
        # while open: the fleet routes around the victim
        status, _h, out = _post(url, {"instances": [[88] * 8]})
        assert status == 200, f"survivors must serve during open: {out}"
        fleet_doc = json.loads(_get(f"{base}/debug/fleet"))
        assert any(r["id"] == victim and r["breaker"] == "open"
                   for r in fleet_doc["replicas"]), fleet_doc["replicas"]
        # fault lifts -> probe traffic half-opens then re-closes the breaker
        _poll(lambda: all(h.engine.step_delay_s == 0.0
                          for h in fleet.live_handles()),
              timeout=15.0, desc="victim replica to recover")
        probe_token = [0]

        def breaker_closed():
            probe_token[0] += 1
            _post(url, {"instances": [[100 + probe_token[0]] * 8]})
            return _metric_value(_get(f"{base}/metrics").decode(),
                                 "fleet_breaker_state", replica=victim) == 0.0

        _poll(breaker_closed, timeout=30.0, interval=0.4,
              desc="breaker to re-close after recovery")
        report["breaker"] = {"victim": victim, "opened": True,
                             "reclosed": True,
                             "deadline_statuses": deadline_statuses}

        # -- (6) crash survival ---------------------------------------------
        crash_target = fleet.live_handles()[-1].gauge_id
        monkey.inject(Fault(at=0.0, kind="crash_replica_mid_decode",
                            target=crash_target))
        crash_burst: list = [None] * 8

        def crash_client(i: int) -> None:
            crash_burst[i] = _post(url, {"instances": [[150 + i] * 8],
                                         "timeout_ms": 120000})

        cts = [threading.Thread(target=crash_client, args=(i,))
               for i in range(len(crash_burst))]
        for t in cts:
            t.start()
        for t in cts:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in cts), "crash-burst clients hung"
        crash_statuses = [c[0] for c in crash_burst]
        assert all(s == 200 for s in crash_statuses), \
            f"fleet must serve through a replica crash: {crash_statuses}"
        report["crash"] = {"target": crash_target, "burst_200": len(cts)}

        # -- (7) every slot and queue reclaimed, counters coherent ----------
        def drained():
            doc = json.loads(_get(f"{base}/debug/fleet"))
            live = [r for r in doc["replicas"]
                    if r["state"] in ("pending", "ready")]
            return all(r["queue_depth"] == 0 and r["active_slots"] == 0
                       for r in live)

        _poll(drained, timeout=30.0, desc="all queues and slots to drain")
        text = _get(f"{base}/metrics").decode()
        expired = _metric_value(text, "serving_deadline_expired_total")
        assert expired >= 1, f"serving_deadline_expired_total={expired}"
        assert _metric_value(text, "serving_shed_total",
                             priority="interactive") == 0
        assert _metric_value(text, "fleet_breaker_state",
                             replica=victim) == 0.0
        report["deadline_expired_total"] = expired
        return report
    finally:
        monkey.stop()
        httpd.close()
        server.close()
        model.close()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
