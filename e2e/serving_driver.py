"""Serving e2e driver — the analog of testing/test_tf_serving.py.

The reference POSTs ``/v1/models/mnist:predict`` at a Service IP, retrying
up to 10 times, and compares predictions against a golden JSON within 1e-3
(test_tf_serving.py:40-57,108-133). Here the served model is the JAX BERT
MLM server (the BASELINE serving config); the golden values come from a
direct in-process apply of the same params, so the check validates the
whole HTTP + batching + padding + jit path bit-for-bit-ish (±1e-3, same
tolerance the reference uses for float comparisons).

Run standalone:  python -m e2e.serving_driver
"""

from __future__ import annotations

import sys
import urllib.error
from typing import Any, Dict, List

import numpy as np

from kubeflow_tpu.serving.server import ModelServer, bert_served_model

from .cluster import http_json
from .junit import run_driver
from .retry import run_with_retry

TOLERANCE = 1e-3  # test_tf_serving.py:40-57 almost_equal tolerance


def almost_equal(a: Any, b: Any, tol: float = TOLERANCE) -> bool:
    return bool(np.allclose(np.asarray(a), np.asarray(b), atol=tol))


def run_serving_e2e(retries: int = 10) -> Dict[str, Any]:
    model = bert_served_model("bert", tiny=True)
    server = ModelServer().add(model)
    http = server.serve(0)
    base = f"http://127.0.0.1:{http.port}"
    try:
        # Golden predictions: direct apply, bypassing HTTP (the reference's
        # result.json analog, computed instead of checked in).
        rng = np.random.default_rng(0)
        instances: List[List[int]] = rng.integers(0, 1000, size=(3, 16)).tolist()
        expected = model.predict(instances)

        # Model status endpoint (GET /v1/models/<name>).
        status = run_with_retry(
            lambda: http_json("GET", f"{base}/v1/models/bert"),
            retries=retries,
            retry_on=(urllib.error.URLError, ConnectionError),
        )
        assert status["model_version_status"][0]["state"] == "AVAILABLE", status

        # Predict with retries (test_tf_serving.py:108-127).
        resp = run_with_retry(
            lambda: http_json("POST", f"{base}/v1/models/bert:predict", {"instances": instances}),
            retries=retries,
            retry_on=(urllib.error.URLError, ConnectionError),
        )
        predictions = resp["predictions"]
        assert len(predictions) == len(instances), (len(predictions), len(instances))
        assert almost_equal(predictions, expected), "served predictions diverge from direct apply"

        # Ragged batch: a second request at a different size must agree
        # (exercises the bucket-padding path). Different batch buckets are
        # separate XLA compilations; on TPU their bf16 MXU tilings differ
        # legitimately, so this cross-shape check uses a relative tolerance
        # (the strict 1e-3 above compares same-shape, same-executable runs).
        resp1 = http_json("POST", f"{base}/v1/models/bert:predict", {"instances": instances[:1]})
        assert np.allclose(
            np.asarray(resp1["predictions"][0]), np.asarray(expected[0]), rtol=5e-2, atol=5e-2
        ), "padding changed predictions beyond accelerator numerics"
        return {"predictions": len(predictions), "model": "bert"}
    finally:
        http.close()


def main(argv=None) -> int:
    return run_driver(
        "e2e-serving",
        "ServingE2E",
        "bert-predict",
        lambda args: run_serving_e2e,
        argv=argv,
        default_junit="junit_serving.xml",
    )


if __name__ == "__main__":
    sys.exit(main())
