"""Distributed-bootstrap e2e: the injected JAX env really forms a cluster.

SURVEY §2.10: the control plane's job for the communication backend is to
(1) schedule the multi-host pod set, (2) inject the coordinator address +
world size (PodDefault webhook; the worker id deliberately derives from the
StatefulSet ordinal at runtime), (3) request the TPU slice. The other e2e
drivers verify (1) and (3); this driver closes the loop on (2): it spawns a
multi-host notebook through the real platform (spawner → CR → controller →
webhook), reads the env actually injected into the pods, then BOOTS one OS
process per worker with exactly that env and runs the REAL library
bootstrap (``kubeflow_tpu.parallel.distributed.initialize`` — identity from
env + pod-hostname ordinal, then ``jax.distributed.initialize``), finishing
with an allgather across the workers. The only substitution is transport:
localhost TCP stands in for the headless-service DNS + ICI (no kube DNS or
multi-chip here; CPU workers rendezvous over the same coordinator
protocol).

Run standalone:  python -m e2e.distributed_driver
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Any, Dict

from kubeflow_tpu.tpu.env import (
    ENV_COORDINATOR_ADDRESS,
    ENV_NUM_PROCESSES,
    env_list_to_dict,
)

from .cluster import (E2ECluster, csrf_headers, free_port, http_json,
                      unique_namespace, wait_for_condition)
from .junit import run_driver

OWNER = "dist-e2e@example.com"
IDENTITY = {"kubeflow-userid": OWNER}



WORKER_PROGRAM = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

# The REAL library bootstrap the notebook images run: identity from the
# injected env, worker ordinal from the (pod) hostname — passed explicitly
# here because this OS process does not carry the pod's hostname.
from kubeflow_tpu.parallel import distributed

ident = distributed.initialize(hostname=os.environ["E2E_POD_NAME"])
assert ident.is_distributed, ident

import jax.numpy as jnp
from jax.experimental import multihost_utils

gathered = multihost_utils.process_allgather(jnp.float32(ident.process_id + 1))
total = float(gathered.sum())
expect = ident.num_processes * (ident.num_processes + 1) / 2
assert total == expect, (total, expect)
print(f"worker {ident.process_id}/{ident.num_processes}: "
      f"allgather={gathered.tolist()} sum={total}", flush=True)

# A REAL data-parallel train step over the multi-process mesh: the global
# batch shards over the process axis, XLA places the gradient all-reduce
# on the inter-process channel (the NCCL/MPI-analog path) — this is the
# SPMD training loop the slice pods run, not just a rendezvous probe.
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("data",))

def loss_fn(w, x):
    y = jnp.tanh(x @ w)
    return jnp.mean((y - 1.0) ** 2)

@jax.jit
def train_step(w, x):
    l, g = jax.value_and_grad(loss_fn)(w, x)
    return w - 0.5 * g, l

# per-process local shard -> one global array (distinct data per worker);
# rows scale with the device count so the mesh tiles evenly whether each
# process has 1 CPU device (standalone) or 8 (the test-suite XLA flag)
rows = jax.local_device_count() * 2
x_local = np.random.RandomState(ident.process_id).randn(rows, 16).astype("float32")
x = multihost_utils.host_local_array_to_global_array(x_local, mesh, P("data"))
w = jax.device_put(jnp.zeros((16, 16), jnp.float32), NamedSharding(mesh, P()))
losses = []
for _ in range(5):
    w, l = train_step(w, x)
    losses.append(float(l))
assert losses[-1] < losses[0], losses
# every process must hold IDENTICAL params after synced updates: gather
# each host's full local copy and compare ELEMENTWISE (a scalar checksum
# is permutation-invariant and cancellation-prone — offsetting deltas
# would pass it)
w_local = np.asarray(jax.device_get(w))
all_w = np.asarray(multihost_utils.process_allgather(jnp.asarray(w_local[None])))
all_w = all_w.reshape(ident.num_processes, *w_local.shape)
assert all(np.allclose(all_w[i], all_w[0], atol=1e-6)
           for i in range(ident.num_processes)), "param replicas diverged"
print(f"worker {ident.process_id}: dp_train losses={losses[0]:.4f}->{losses[-1]:.4f} "
      f"params_synced=True", flush=True)

# -- composed dp x tp over the SAME process set (VERDICT r4 #9) -------------
# DCN x ICI shape: the data axis crosses the process boundary (the
# inter-host gradient all-reduce rides the coordinator-bootstrapped
# channel), the model axis stays inside each process's local devices (the
# ICI analog — v5e 2x4 is 2 hosts x 4 chips, exactly this mesh). A
# Megatron-split 2-layer MLP: W1 column-sharded, W2 row-sharded; XLA
# inserts the activation reduce + dp gradient psum.
n_local = jax.local_device_count()
devs = np.array(jax.devices()).reshape(ident.num_processes, n_local)
mesh2 = Mesh(devs, ("data", "model"))
D, H = 16, 16 * n_local
w1 = jax.device_put(
    jnp.asarray(np.random.RandomState(0).randn(D, H) * 0.1, jnp.float32),
    NamedSharding(mesh2, P(None, "model")))
w2 = jax.device_put(
    jnp.asarray(np.random.RandomState(1).randn(H, D) * 0.1, jnp.float32),
    NamedSharding(mesh2, P("model", None)))

def tp_loss(params, x):
    w1, w2 = params
    return jnp.mean((jnp.tanh(x @ w1) @ w2 - 1.0) ** 2)

@jax.jit
def tp_step(params, x):
    l, g = jax.value_and_grad(tp_loss)(params, x)
    return tuple(w - 0.5 * dw for w, dw in zip(params, g)), l

x2_local = np.random.RandomState(100 + ident.process_id).randn(
    2 * n_local, D).astype("float32")
x2 = multihost_utils.host_local_array_to_global_array(x2_local, mesh2, P("data", None))
params = (w1, w2)
tp_losses = []
for _ in range(5):
    params, l = tp_step(params, x2)
    tp_losses.append(float(l))
assert tp_losses[-1] < tp_losses[0], tp_losses
# parity: replicate each sharded param, then compare every host's copy
# ELEMENTWISE across processes (same rationale as the dp check above)
for name, w in zip(("w1", "w2"), params):
    w_rep = jax.jit(lambda a: a, out_shardings=NamedSharding(mesh2, P()))(w)
    local = np.asarray(w_rep.addressable_data(0))
    gathered = np.asarray(multihost_utils.process_allgather(jnp.asarray(local[None])))
    gathered = gathered.reshape(ident.num_processes, *local.shape)
    assert all(np.allclose(gathered[i], gathered[0], atol=1e-6)
               for i in range(ident.num_processes)), f"{name} replicas diverged"
print(f"worker {ident.process_id}: dp_tp_train mesh=data{ident.num_processes}"
      f"xmodel{n_local} losses={tp_losses[0]:.4f}->{tp_losses[-1]:.4f} "
      f"tp_params_synced=True", flush=True)
"""


def run_distributed_e2e(timeout: float = 120.0) -> Dict[str, Any]:
    with E2ECluster() as cluster:
        ns = cluster.create_profile(OWNER, unique_namespace("dist"))
        config_name = "tpu-v5e-2x4"
        cluster.client.create({
            "apiVersion": "kubeflow.org/v1alpha1",
            "kind": "PodDefault",
            "metadata": {"name": config_name, "namespace": ns},
            "spec": {
                "desc": "TPU v5e 2x4 slice",
                "selector": {"matchLabels": {config_name: "true"}},
                "tpu": {"generation": "v5e", "topology": "2x4"},
            },
        })

        base = cluster.serve_jupyter()
        headers = csrf_headers(base, IDENTITY)
        http_json("POST", f"{base}/api/namespaces/{ns}/notebooks", {
            "name": "dist-nb",
            "image": "kubeflow-tpu/jupyter-jax-tpu:latest",
            # the slice selection sizes the StatefulSet to the host count;
            # the PodDefault label wires the TPU env/limit injection
            "tpus": {"generation": "v5e", "topology": "2x4"},
            "configurations": [config_name],
        }, headers)

        def pods_running():
            pods = [p for p in cluster.client.list("v1", "Pod", ns)
                    if p["metadata"]["name"].startswith("dist-nb-")]
            return pods if len(pods) >= 2 and all(
                p.get("status", {}).get("phase") == "Running" for p in pods) else None

        pods = wait_for_condition(pods_running, timeout=timeout, desc="slice pods running")

        # The env the webhook ACTUALLY injected into each pod. Worker id is
        # NOT injected — by design it derives from the StatefulSet ordinal
        # (pod hostname) at runtime, which the worker program exercises.
        worker_envs = []
        for pod in sorted(pods, key=lambda p: p["metadata"]["name"]):
            env = env_list_to_dict(pod["spec"]["containers"][0].get("env", []))
            assert ENV_COORDINATOR_ADDRESS in env and ENV_NUM_PROCESSES in env, env
            worker_envs.append((pod["metadata"]["name"], env))
        nproc = int(worker_envs[0][1][ENV_NUM_PROCESSES])
        assert nproc == len(worker_envs), (nproc, len(worker_envs))

        # Boot one real OS process per worker with that env; localhost TCP
        # stands in for the headless-service DNS the address names.
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        coord_port = free_port()
        procs = []
        try:
            for pod_name, env in worker_envs:
                penv = dict(os.environ)
                penv.update(env)
                penv[ENV_COORDINATOR_ADDRESS] = f"127.0.0.1:{coord_port}"
                penv["E2E_POD_NAME"] = pod_name
                penv["PYTHONPATH"] = repo_root + os.pathsep + os.environ.get("PYTHONPATH", "")
                # 4 virtual devices per process: the v5e 2x4 host shape
                # (2 hosts x 4 chips) for the dp x tp phase — replace any
                # inherited device-count flag (the test suite sets 8)
                flags = [f for f in penv.get("XLA_FLAGS", "").split()
                         if "xla_force_host_platform_device_count" not in f]
                flags.append("--xla_force_host_platform_device_count=4")
                penv["XLA_FLAGS"] = " ".join(flags)
                procs.append(subprocess.Popen(
                    [sys.executable, "-c", WORKER_PROGRAM],
                    env=penv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                ))
            outputs = []
            for p in procs:
                out, _ = p.communicate(timeout=timeout)
                outputs.append(out.decode())
                assert p.returncode == 0, out.decode()[-2000:]
            assert all("allgather=" in o for o in outputs)
            assert all("dp_train" in o and "params_synced=True" in o for o in outputs)
            assert all("dp_tp_train" in o and "tp_params_synced=True" in o
                       for o in outputs), "dp x tp phase missing"
        finally:
            # a failed/hung worker must not survive the run holding the
            # fixed coordinator port for every later invocation
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        return {
            "namespace": ns,
            "workers": nproc,
            "coordinator_env": worker_envs[0][1][ENV_COORDINATOR_ADDRESS],
            "rendezvous": "ok",
            "dp_train": "ok",
            "dp_tp_train": f"ok (data{nproc} x model4, DCN x ICI shape)",
        }


def main(argv=None) -> int:
    return run_driver(
        "e2e-distributed",
        "DistributedBootstrapE2E",
        lambda args: "jax-coordinator-rendezvous",
        lambda args: lambda: run_distributed_e2e(),
        argv=argv,
        default_junit="junit_distributed.xml",
    )


if __name__ == "__main__":
    raise SystemExit(main())
