"""Goodput-ledger e2e: account every wallclock second of a chaotic run.

An ElasticTrainer survives scripted chaos (two graceful preemptions plus
one hard gang loss) on the 8-virtual-device dryrun topology while a
GoodputLedger decomposes its incarnation-spanning wallclock, CI job
goodput-e2e:

1. the composed-4D GPT trains as a drain-graced ``trial`` gang; a
   StepClock on the workload separates XLA compile and data-wait from
   compute inside every step;
2. chaos preempts the gang twice gracefully (urgent checkpoint + ack, zero
   replay) and once HARD (pods deleted without drain, timed so the next
   incarnation must replay exactly the steps past the last periodic
   checkpoint);
3. a 4-chip gang in namespace ``tenant-a`` is bound for the whole run so
   ``tenant_chip_seconds_total`` can be checked against chips × measured
   bound duration;
4. after training, the monitoring plane scrapes this process's /metrics
   over real HTTP, evaluates the ``platform:training_goodput_fraction``
   recording rule, and the dashboard's ``/api/metrics/platform`` reports
   the goodput and tenants sections from the federated TSDB.

Asserts the ledger's honesty contract: fractions sum to EXACTLY 1.0, the
named buckets reconstruct the driver-measured wallclock within 5%,
``preemption_replay`` and ``checkpoint_restore`` are strictly positive (and
the replay is exactly the steps past the surviving checkpoint),
``scheduling_wait`` matches the scheduler's own bind-latency observations,
and the tenant meter agrees with chips × bound-duration within a scrape
interval.

CPU-only; per-incarnation jit compiles dominate the ~minutes runtime.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )

import json
import shutil
import tempfile
import time
from typing import Optional

from e2e.junit import run_driver

NAMESPACE = "default"
TENANT_NS = "tenant-a"
TOTAL_STEPS = 24
CKPT_EVERY = 4
GRACE_SECONDS = 20.0
STEP_SLEEP = 0.03
RECONSTRUCTION_TOL = 0.05
#: bind-latency timestamps have 1s resolution (creationTimestamp), so the
#: cross-check slack scales with the number of observed gangs
BIND_LATENCY_SLACK_PER_GANG = 1.5
#: tenant meter tolerance: chips × (bind-observe + unbind-settle delays)
TENANT_TOL_CHIP_SECONDS = 8.0

#: the one slice shape: both 2x4 hosts (the spare host is the tenant's)
SHAPE = {"pods": 2, "chips": 4, "pp": 4, "virtual": 1}


def _poll(fn, timeout: float = 30.0, interval: float = 0.05, desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _gang_pod(name, gang, size, chips, priority_class, namespace=NAMESPACE,
              grace=None):
    from kubeflow_tpu.api.meta import new_object
    from kubeflow_tpu.scheduler.gang import (
        DRAIN_GRACE_ANNOTATION,
        POD_GROUP_LABEL,
        POD_GROUP_SIZE_ANNOTATION,
    )
    from kubeflow_tpu.tpu.topology import RESOURCE_TPU

    annotations = {POD_GROUP_SIZE_ANNOTATION: str(size)}
    if grace is not None:
        annotations[DRAIN_GRACE_ANNOTATION] = str(grace)
    return new_object(
        "v1", "Pod", name, namespace,
        labels={POD_GROUP_LABEL: gang},
        annotations=annotations,
        spec={
            "priorityClassName": priority_class,
            "containers": [{
                "name": "trainer",
                "resources": {"limits": {RESOURCE_TPU: str(chips)}},
            }],
        },
    )


class SliceRequester:
    """Gang acquisition against the real scheduler, one fixed shape."""

    def __init__(self, client, devices):
        self._client = client
        self._devices = list(devices)
        self.gen = 0
        self.current_gang: Optional[str] = None
        self.current_pods: list = []

    def __call__(self, attempt: int):
        from kubeflow_tpu.training.elastic import SliceOffer

        self.gen += 1
        gang = f"train-g{self.gen}"
        names = [f"{gang}-{i}" for i in range(SHAPE["pods"])]
        for n in names:
            self._client.create(_gang_pod(
                n, gang, SHAPE["pods"], SHAPE["chips"], "trial",
                grace=GRACE_SECONDS))
        _poll(lambda: self._all_running(names), timeout=30.0,
              desc=f"gang {gang} running")
        self.current_gang = gang
        self.current_pods = names
        return SliceOffer(
            devices=self._devices[: SHAPE["pods"] * SHAPE["chips"]],
            pp=SHAPE["pp"], virtual_stages=SHAPE["virtual"],
            pods=names, namespace=NAMESPACE,
        )

    def _all_running(self, names) -> bool:
        pods = [self._client.get_opt("v1", "Pod", n, NAMESPACE) for n in names]
        return all(p is not None and (p.get("status") or {}).get("phase") == "Running"
                   for p in pods)


def run(args) -> dict:
    import jax

    from kubeflow_tpu.controllers.builtin import PodletReconciler, make_tpu_node
    from kubeflow_tpu.monitoring.goodput import TENANT_METER
    from kubeflow_tpu.parallel.composite import CompositeConfig
    from kubeflow_tpu.runtime.chaos import ChaosMonkey, ChaosSchedule, Fault
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.scheduler import SchedulerReconciler
    from kubeflow_tpu.tpu.profiling import StepClock
    from kubeflow_tpu.training.checkpoint import Checkpointer
    from kubeflow_tpu.training.elastic import (
        CompositeWorkload,
        ElasticTrainer,
        PreemptionHandler,
    )

    devices = jax.devices()
    assert len(devices) == 8, f"driver needs 8 virtual devices, got {len(devices)}"
    cfg = CompositeConfig(n_layers=8, vocab_size=64)

    mgr = Manager()
    mgr.add(SchedulerReconciler(
        assembly_timeout=5.0, reservation_ttl=5.0,
        backoff_base=0.05, backoff_cap=0.4))
    mgr.add(PodletReconciler())
    client = mgr.client
    client.create(make_tpu_node("tpu-node-0", "v5e", "2x4", 4))
    client.create(make_tpu_node("tpu-node-1", "v5e", "2x4", 4))
    client.create(make_tpu_node("tpu-spare", "v5e", "2x2", 4))
    mgr.start()

    # -- the metered tenant: one 4-chip gang bound for the whole run ----------
    client.create(_gang_pod("meter-0", "meter", 1, 4, "trial",
                            namespace=TENANT_NS))
    _poll(lambda: ((client.get_opt("v1", "Pod", "meter-0", TENANT_NS) or {})
                   .get("status") or {}).get("phase") == "Running",
          desc="tenant gang running")
    tenant_bound_at = time.monotonic()

    ckpt_dir = tempfile.mkdtemp(prefix="goodput-e2e-")
    requester = SliceRequester(client, devices)
    monkey = ChaosMonkey(client, ChaosSchedule([]), store=mgr.store)

    # -- scripted badput ------------------------------------------------------
    # gens 1 & 2: GRACEFUL chaos preemption (urgent save + ack → zero replay)
    # gen 3: HARD loss — pods deleted with no drain signal, timed on a step
    # ≡ 1 (mod CKPT_EVERY) so the surviving checkpoint (saved at step ≡ 3)
    # forces the next incarnation to replay exactly 2 steps
    fired = set()

    def graceful_preempt():
        monkey.inject(Fault(
            0.0, "preempt_gang", f"{NAMESPACE}/{requester.current_gang}",
            param=GRACE_SECONDS))

    def hard_kill():
        for n in requester.current_pods:
            client.delete_opt("v1", "Pod", n, NAMESPACE)

    def maybe_fire(gen: int, local: int, step: int) -> None:
        if gen in (1, 2) and local == 2 and gen not in fired:
            fired.add(gen)
            graceful_preempt()
        elif (gen == 3 and 3 not in fired and local >= 1
              and step % CKPT_EVERY == 1):
            fired.add(3)
            hard_kill()

    class DrivenWorkload(CompositeWorkload):
        def __init__(self, **kw):
            super().__init__(**kw)
            self._gen = None
            self._local = 0

        def run_step(self, state, step):
            state, loss = super().run_step(state, step)
            if requester.gen != self._gen:
                self._gen, self._local = requester.gen, 0
            maybe_fire(self._gen, self._local, step)
            self._local += 1
            time.sleep(STEP_SLEEP)
            return state, loss

    workload = DrivenWorkload(cfg=cfg, num_micro=4, microbatch=4,
                              clock=StepClock())
    trainer = ElasticTrainer(
        workload,
        Checkpointer(ckpt_dir, max_to_keep=3),
        requester,
        TOTAL_STEPS,
        checkpoint_every=CKPT_EVERY,
        handler_factory=lambda offer: PreemptionHandler(
            client, NAMESPACE, offer.pods, poll_interval=0.02),
    )

    try:
        t0 = time.perf_counter()
        report = trainer.run()
        elapsed = time.perf_counter() - t0

        # -- settle the tenant interval before reading the meter --------------
        client.delete_opt("v1", "Pod", "meter-0", TENANT_NS)
        _poll(lambda: TENANT_NS not in TENANT_METER.open_intervals(),
              desc="tenant interval settled")
        tenant_unbound_at = time.monotonic()
    finally:
        monkey.stop()

    try:
        # -- survival ---------------------------------------------------------
        assert report.completed, f"training never finished: {report.incarnations}"
        assert report.preemptions_survived >= 2, report.incarnations
        assert fired == {1, 2, 3}, f"unfired chaos phases: {fired}"
        outcomes = [i["outcome"] for i in report.incarnations]
        assert "lost" in outcomes, f"hard loss never happened: {outcomes}"

        # -- the honesty contract --------------------------------------------
        snap = trainer.goodput.snapshot()
        fraction_sum = sum(snap["fractions"].values())
        assert fraction_sum == 1.0, \
            f"fractions must sum to exactly 1.0, got {fraction_sum!r}"
        assert snap["reconstructionError"] <= RECONSTRUCTION_TOL, (
            "named buckets fail to reconstruct wallclock: "
            f"{snap['reconstructionError']:.4f} > {RECONSTRUCTION_TOL}; "
            f"decomposition: {snap['badputSeconds']}")
        wall_delta = abs(snap["wallclockSeconds"] - elapsed) / elapsed
        assert wall_delta <= RECONSTRUCTION_TOL, (
            f"ledger wallclock {snap['wallclockSeconds']:.2f}s vs driver "
            f"{elapsed:.2f}s ({wall_delta:.1%})")

        # -- attribution: chaos lands in named buckets, not `other` ----------
        bad = snap["badputSeconds"]
        assert bad["preemption_replay"] > 0.0, bad
        assert bad["checkpoint_restore"] > 0.0, bad
        assert bad["checkpoint_save"] > 0.0, bad
        assert bad["compile"] > 0.0, "StepClock compile never drained"
        assert bad["scheduling_wait"] > 0.0, bad
        replayed = sum(i["goodput"]["replaySteps"] for i in report.incarnations)
        assert replayed == 2, (
            f"hard loss on step ≡ 1 (mod {CKPT_EVERY}) must replay exactly "
            f"2 steps, replayed {replayed}")
        # graceful drains urgent-save at the drained step: every non-lost
        # handover resumes at endStep+1 with zero replay
        for prev, cur in zip(report.incarnations, report.incarnations[1:]):
            if prev["outcome"] == "preempted":
                assert cur["startStep"] == prev["endStep"] + 1, (prev, cur)
                assert cur["goodput"]["replaySteps"] == 0, cur

        # -- scheduling_wait vs the scheduler's own bind-latency signal ------
        bind = METRICS.histogram("scheduler_bind_latency_seconds")
        assert bind.total >= len(report.incarnations), bind.total
        slack = BIND_LATENCY_SLACK_PER_GANG * bind.total
        assert abs(bad["scheduling_wait"] - bind.sum) <= slack, (
            f"scheduling_wait {bad['scheduling_wait']:.2f}s vs scheduler "
            f"bind latency {bind.sum:.2f}s over {int(bind.total)} gangs")

        # -- satellite histograms --------------------------------------------
        restore_h = METRICS.histogram("checkpoint_restore_seconds")
        assert restore_h.total >= 3, restore_h.total  # one per re-incarnation
        assert METRICS.total("training_badput_seconds_total") > 0.0
        assert METRICS.value("training_badput_seconds_total",
                             bucket="preemption_replay") > 0.0
        goodput_fraction = METRICS.value("training_goodput_fraction",
                                         workload="training")
        assert goodput_fraction > 0.0
        # the gauge publishes round(fraction, 6)
        assert abs(goodput_fraction - snap["goodputFraction"]) <= 1e-6, (
            goodput_fraction, snap["goodputFraction"])

        # -- tenant metering: chips × bound duration --------------------------
        expected_chip_s = 4 * (tenant_unbound_at - tenant_bound_at)
        actual_chip_s = METRICS.value("tenant_chip_seconds_total",
                                      namespace=TENANT_NS)
        assert abs(actual_chip_s - expected_chip_s) <= TENANT_TOL_CHIP_SECONDS, (
            f"tenant_chip_seconds_total={actual_chip_s:.2f} vs "
            f"chips×duration={expected_chip_s:.2f}")

        # -- federation: scrape → TSDB → recording rule → dashboard ----------
        monitoring = monitoring_phase(client, snap)

        summary = {
            "ok": True,
            "elapsed_seconds": round(elapsed, 1),
            "preemptions_survived": report.preemptions_survived,
            "incarnations": [
                {k: v for k, v in i.items() if k != "offer"}
                for i in report.incarnations
            ],
            "goodput_fraction": round(snap["goodputFraction"], 4),
            "reconstruction_error": round(snap["reconstructionError"], 4),
            "badput_seconds": {k: round(v, 3) for k, v in bad.items()},
            "replayed_steps": replayed,
            "tenant_chip_seconds": round(actual_chip_s, 2),
            "monitoring": monitoring,
        }
        # metric line for the GOODPUT_r* bench-gate family
        print(json.dumps({"metric": "training_goodput_fraction",
                          "value": round(snap["goodputFraction"], 4)}))
        print(json.dumps(summary))
        return summary
    finally:
        mgr.stop()
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def monitoring_phase(client, snap) -> dict:
    """Scrape this process over real HTTP into a MonitoringPlane, evaluate
    the goodput recording rule, and read the dashboard's goodput/tenants
    sections from the federated TSDB."""
    from kubeflow_tpu.api.meta import new_object
    from kubeflow_tpu.monitoring import (
        SCRAPE_ANNOTATION,
        SCRAPE_JOB_ANNOTATION,
        SCRAPE_URL_ANNOTATION,
        MonitoringPlane,
        goodput_recording_rules,
    )
    from kubeflow_tpu.runtime.obs import mount_observability
    from kubeflow_tpu.services.dashboard import make_dashboard_app
    from kubeflow_tpu.web.auth import AuthConfig
    from kubeflow_tpu.web.http import App

    app = App("trainer")
    mount_observability(app)
    httpd = app.serve(0)
    try:
        client.create(new_object(
            "v1", "Pod", "goodput-target", NAMESPACE,
            annotations={
                SCRAPE_ANNOTATION: "true",
                SCRAPE_URL_ANNOTATION:
                    f"http://127.0.0.1:{httpd.port}/metrics",
                SCRAPE_JOB_ANNOTATION: "training",
            }))
        plane = MonitoringPlane(client=client, stale_after=10, timeout_s=5.0)
        for rule in goodput_recording_rules():
            plane.rules.add(rule)
        up = plane.scraper.scrape_once()
        assert up and all(up.values()), f"scrape target not up: {up}"
        plane.tick()

        scraped = {lab.get("workload"): v for lab, _t, v in
                   plane.tsdb.latest("training_goodput_fraction")}
        assert scraped.get("training") is not None, scraped
        assert abs(scraped["training"] - snap["goodputFraction"]) < 1e-3, (
            scraped, snap["goodputFraction"])
        recorded = [v for _l, _t, v in
                    plane.tsdb.latest("platform:training_goodput_fraction")]
        assert recorded and 0.0 < recorded[0] <= 1.0, (
            f"recording rule produced {recorded}")
        assert list(plane.tsdb.latest("tenant_chip_seconds_total")), \
            "tenant chip meter not federated"

        dash = make_dashboard_app(client, auth=AuthConfig(disable_auth=True),
                                  monitoring=plane)
        overview = dash.call("GET", "/api/metrics/platform?window=60",
                             None, {"kubeflow-userid": "ops@example.com"})
        assert overview.status == 200, overview.body
        doc = overview.body
        gp = doc["goodput"]
        assert gp["trainingGoodputFraction"], gp
        assert gp["trainingBadputSeconds"].get("preemption_replay", 0) > 0, gp
        tenants = {t["namespace"]: t for t in doc["tenants"]}
        assert TENANT_NS in tenants and tenants[TENANT_NS]["chipSeconds"] > 0, \
            doc["tenants"]
        return {
            "scraped_goodput_fraction": round(scraped["training"], 4),
            "recorded_measured_fraction": round(recorded[0], 4),
            "dashboard_tenants": sorted(tenants),
        }
    finally:
        httpd.close()


def main(argv=None) -> int:
    return run_driver(
        suite_name="goodput-e2e",
        class_name="GoodputLedgerDryrun",
        case_name=f"reconcile-wallclock-{TOTAL_STEPS}-steps-3-preemptions",
        make_case=lambda args: lambda: run(args),
        argv=argv,
    )


if __name__ == "__main__":
    raise SystemExit(main())
