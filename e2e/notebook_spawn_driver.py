"""Notebook spawn e2e driver — the HTTP-level analog of testing/test_jwa.py.

The reference drives the Jupyter web app through Selenium (test_jwa.py +
auth.py: log in, click spawn, wait for the notebook). This driver exercises
the same product flow over the real HTTP API, end to end through every
layer the platform owns (SURVEY.md §3.1 call stack):

  spawner POST (CSRF + identity headers)
    → Notebook CR → notebook-controller → StatefulSet(hosts) + Services
    → PodDefault webhook injects google.com/tpu limits + JAX env
    → fake scheduler binds pods to TPU nodes → Running
  then stop (annotation → replicas 0), restart, delete (GC cascade).

Run standalone:  python -m e2e.notebook_spawn_driver
"""

from __future__ import annotations

import sys
from typing import Any, Dict

from kubeflow_tpu.tpu.env import (
    ENV_COORDINATOR_ADDRESS,
    ENV_NUM_PROCESSES,
    ENV_WORKER_HOSTNAMES,
    env_list_to_dict,
)
from kubeflow_tpu.tpu.topology import RESOURCE_TPU

from .cluster import E2ECluster, csrf_headers, http_json, unique_namespace, wait_for_condition
from .junit import run_driver

NOTEBOOK_API = "kubeflow.org/v1beta1"
OWNER = "spawn-e2e@example.com"
IDENTITY = {"kubeflow-userid": OWNER}


def tpu_poddefault(ns: str, name: str, generation: str, topology: str) -> Dict[str, Any]:
    """The per-namespace TPU configuration an admin publishes; the spawner's
    ``configurations`` field selects it by label (SURVEY.md §7 step 2)."""
    return {
        "apiVersion": "kubeflow.org/v1alpha1",
        "kind": "PodDefault",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "desc": f"TPU {generation} {topology} slice",
            "selector": {"matchLabels": {name: "true"}},
            "tpu": {"generation": generation, "topology": topology},
        },
    }


def run_notebook_spawn_e2e(timeout: float = 60.0) -> Dict[str, Any]:
    with E2ECluster() as cluster:
        ns = cluster.create_profile(OWNER, unique_namespace("spawn"))
        config_name = "tpu-v5e-2x4"
        cluster.client.create(tpu_poddefault(ns, config_name, "v5e", "2x4"))

        base = cluster.serve_jupyter()
        headers = csrf_headers(base, IDENTITY)

        # Discovery: the spawner sees the fake node pool's generations and
        # topologies (the reference's /api/gpus vendor discovery, get.py:50-71).
        tpus = http_json("GET", f"{base}/api/tpus", headers=IDENTITY)["tpus"]
        v5e = next(t for t in tpus if t["generation"] == "v5e")
        assert "2x4" in v5e["topologies"], v5e
        pds = http_json("GET", f"{base}/api/namespaces/{ns}/poddefaults", headers=IDENTITY)
        assert any(pd["name"] == config_name for pd in pds["poddefaults"]), pds

        # Spawn: TPU topology + the PodDefault configuration label.
        http_json(
            "POST",
            f"{base}/api/namespaces/{ns}/notebooks",
            {
                "name": "nb-e2e",
                "tpus": {"generation": "v5e", "topology": "2x4"},
                "configurations": [config_name],
            },
            headers,
        )

        def notebook_phase() -> str:
            nbs = http_json("GET", f"{base}/api/namespaces/{ns}/notebooks", headers=IDENTITY)
            for nb in nbs.get("notebooks", []):
                if nb["name"] == "nb-e2e":
                    return nb["status"]["phase"]
            return ""

        def nb_pods():
            return [
                p
                for p in cluster.client.list("v1", "Pod", ns)
                if p["metadata"].get("labels", {}).get("notebook-name") == "nb-e2e"
            ]

        wait_for_condition(lambda: notebook_phase() == "ready", timeout, desc="notebook ready")

        # One pod per slice host, each with chips + deterministic JAX env.
        pods = nb_pods()
        assert len(pods) == 2, f"2x4 v5e slice = 2 hosts, got {len(pods)} pods"
        hostnames = set()
        for pod in pods:
            container = pod["spec"]["containers"][0]
            assert container["resources"]["limits"][RESOURCE_TPU] == "4", container
            # Injected env is identical on every host (webhook determinism);
            # worker ids derive from the StatefulSet ordinal at runtime.
            env = env_list_to_dict(container["env"])
            assert env[ENV_COORDINATOR_ADDRESS].startswith("nb-e2e-0.nb-e2e."), env
            assert env[ENV_NUM_PROCESSES] == "2", env
            assert len(env[ENV_WORKER_HOSTNAMES].split(",")) == 2, env
            hostnames.add(pod["spec"].get("hostname", ""))
            assert pod["spec"].get("nodeName", "").startswith("tpu-v5e-2x4-"), pod["spec"]
        assert hostnames == {"nb-e2e-0", "nb-e2e-1"}, hostnames

        # Stop: annotation scales the whole slice to zero (culler.go:37 path).
        http_json(
            "PATCH", f"{base}/api/namespaces/{ns}/notebooks/nb-e2e", {"stopped": True}, headers
        )
        wait_for_condition(lambda: notebook_phase() == "stopped", timeout, desc="notebook stopped")
        wait_for_condition(lambda: not nb_pods(), timeout, desc="slice released")

        # Restart: chips reacquired, back to ready.
        http_json(
            "PATCH", f"{base}/api/namespaces/{ns}/notebooks/nb-e2e", {"stopped": False}, headers
        )
        wait_for_condition(lambda: notebook_phase() == "ready", timeout, desc="notebook restarted")

        # Delete: CR gone and children garbage-collected.
        http_json("DELETE", f"{base}/api/namespaces/{ns}/notebooks/nb-e2e", headers=headers)
        wait_for_condition(lambda: notebook_phase() == "", timeout, desc="notebook deleted")
        wait_for_condition(
            lambda: not cluster.client.list("apps/v1", "StatefulSet", ns) and not nb_pods(),
            timeout,
            desc="children garbage-collected",
        )
        return {"namespace": ns, "hosts": 2}


def main(argv=None) -> int:
    def add_args(parser):
        parser.add_argument("--timeout", type=float, default=60.0)

    return run_driver(
        "e2e-notebook-spawn",
        "NotebookSpawnE2E",
        "spawn-stop-restart-delete",
        lambda args: lambda: run_notebook_spawn_e2e(args.timeout),
        argv=argv,
        add_args=add_args,
        default_junit="junit_notebook_spawn.xml",
    )


if __name__ == "__main__":
    sys.exit(main())
