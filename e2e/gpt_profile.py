"""GPT-2-medium train-step decomposition (the stage_profile analog for the
transformer flagship).

Times each phase of the b8 x L1024 training step AS TRAINED (bf16 compute,
AdamW, Pallas flash attention), isolated into its own scanned tower with
the standard anti-hoist carry and host-fetch barrier:

  block      one transformer block fwd+bwd (x24 = the model body)
  embed_head embedding + final LN + tied LM head + CE loss fwd+bwd
  optimizer  AdamW update alone over the full param set

The full-step reference point is the bench itself (`BENCH_MODEL=gpt
python bench.py`, ~218 ms at 42.4% MFU). NOTE the towers are bounds, not
addends: 24 x block measured ABOVE the full step — XLA schedules the full
graph better than any isolated piece (BASELINE.md round-4 notes).

Run:  python -m e2e.gpt_profile [--batch 8] [--seq 1024]
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import optax

# one copy of the honest timing harness (and its compile-cache setup):
# importing ceiling applies the jax_compilation_cache_dir config too
from e2e.ceiling import _timed as _scan_time


def profile(batch: int = 8, seq: int = 1024, steps: int = 20) -> List[Dict[str, Any]]:
    from kubeflow_tpu.models.gpt import GptBlock, GptConfig, GptLM, causal_lm_loss

    cfg = GptConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096,
                    max_seq=seq, vocab_size=32000)
    rng = jax.random.PRNGKey(0)
    ids = jax.random.randint(rng, (batch, seq), 0, cfg.vocab_size)
    rows: List[Dict[str, Any]] = []

    # -- one transformer block fwd+bwd --------------------------------------
    block = GptBlock(cfg)
    x0 = jax.random.normal(rng, (batch, seq, cfg.d_model), jnp.bfloat16) * 0.1
    positions = jnp.arange(seq)
    bparams = block.init(rng, x0, positions)["params"]

    def block_loss(p, x):
        return jnp.sum(jnp.abs(block.apply({"params": p}, x, positions)
                               .astype(jnp.float32))) * 1e-6

    @jax.jit
    def run_block(p, x):
        def body(c, _):
            xx = x + c * jnp.bfloat16(1e-30)
            loss, grads = jax.value_and_grad(block_loss)(p, xx)
            gsum = sum(jnp.sum(g.astype(jnp.float32))
                       for g in jax.tree_util.tree_leaves(grads))
            return c + jnp.bfloat16(loss * 1e-6 + gsum * 1e-30), ()
        c, _ = jax.lax.scan(body, jnp.bfloat16(0), None, length=steps)
        return c

    dt = _scan_time(run_block, (bparams, x0), steps)
    # per-block fwd FLOPs: 4 attn projections + 2 mlp matmuls + attention
    proj = 4 * 2.0 * batch * seq * cfg.d_model * cfg.d_model
    mlp = 2 * 2.0 * batch * seq * cfg.d_model * cfg.d_ff
    attn = 2 * 2.0 * batch * cfg.n_heads * seq * seq * cfg.head_dim / 2  # causal
    fl = 3.0 * (proj + mlp + attn)
    rows.append({"phase": "block (x1)", "ms": dt * 1e3, "tflops": fl / dt / 1e12,
                 "x24_ms": dt * 24 * 1e3})

    # -- embedding + LM head + loss fwd+bwd ---------------------------------
    import flax.linen as nn

    class EmbedHead(nn.Module):
        @nn.compact
        def __call__(self, ids):
            embed = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype,
                             param_dtype=jnp.float32, name="embedding")
            x = embed(ids)  # stand-in body output
            x = nn.LayerNorm(dtype=jnp.float32, param_dtype=jnp.float32)(x)
            return x.astype(jnp.float32) @ embed.embedding.T.astype(jnp.float32)

    eh = EmbedHead()
    ehp = eh.init(rng, ids)["params"]

    def eh_loss(p, ids):
        return causal_lm_loss(eh.apply({"params": p}, ids), ids)

    @jax.jit
    def run_eh(p, ids):
        def body(c, _):
            # anti-hoist: roll the ids by the carry so the body is NOT
            # loop-invariant (a fixed (p, ids) body gets hoisted out of the
            # scan and the probe times one execution across all steps)
            ids2 = jnp.roll(ids, jnp.int32(c) + 1, axis=1)
            loss, grads = jax.value_and_grad(eh_loss)(p, ids2)
            gsum = sum(jnp.sum(g.astype(jnp.float32))
                       for g in jax.tree_util.tree_leaves(grads))
            # *1e-30, never *0 — an algebraic zero would DCE the grads
            return c + 1.0 + (loss + gsum) * 1e-30, ()
        c, _ = jax.lax.scan(body, jnp.float32(0), None, length=steps)
        return c

    dt = _scan_time(run_eh, (ehp, ids), steps)
    head = 2.0 * batch * seq * cfg.d_model * cfg.vocab_size
    rows.append({"phase": "embed+head+loss", "ms": dt * 1e3,
                 "tflops": 3.0 * head / dt / 1e12})

    # -- optimizer alone ------------------------------------------------------
    model = GptLM(cfg)
    params = model.init(rng, ids)["params"]
    opt = optax.adamw(3e-4, weight_decay=0.01)
    ostate = opt.init(params)
    fake_grads = jax.tree_util.tree_map(lambda p: (p * 1e-3).astype(p.dtype), params)

    @jax.jit
    def run_opt(params, ostate, grads):
        def body(carry, _):
            p, s = carry
            updates, s = opt.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            return (p, s), ()
        (p, s), _ = jax.lax.scan(body, (params, ostate), None, length=steps)
        return sum(jnp.sum(x.astype(jnp.float32)) for x in jax.tree_util.tree_leaves(p))

    dt = _scan_time(run_opt, (params, ostate, fake_grads), steps)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    rows.append({"phase": "adamw update", "ms": dt * 1e3,
                 "gb_moved": round(n_params * 4 * 7 / 1e9, 2)})

    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args(argv)
    rows = profile(args.batch, args.seq, args.steps)
    total = 0.0
    for r in rows:
        extra = f"  (x24 = {r['x24_ms']:.1f} ms)" if "x24_ms" in r else ""
        rate = f"{r['tflops']:6.1f} TF/s" if "tflops" in r else f"{r.get('gb_moved', '?')} GB/step"
        print(f"{r['phase']:18s} {r['ms']:8.2f} ms  {rate}{extra}", flush=True)
        total += r.get("x24_ms", r["ms"])
    print(f"{'sum (24 blocks + head + opt)':18s} {total:8.2f} ms")
    print(json.dumps({"metric": "gpt_step_profile", "batch": args.batch,
                      "seq": args.seq, "rows": rows, "sum_ms": round(total, 2)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
