"""Device-ceiling probe: what this chip/tunnel actually sustains.

VERDICT r2 #3: the "tunnel caps us at ~61 TFLOP/s" claim was asserted from a
SINGLE-dispatch matmul (per-dispatch tunnel latency dominated it — the same
artifact BASELINE.md's integrity note documents for naive step timing) while
the ResNet number came from an amortized 50-step scan. This probe measures
every kernel the same honest way the bench does: all iterations inside ONE
jitted ``lax.scan`` executable, results kept live by a fetched checksum, a
device→host fetch as the barrier.

Kernels:
- bf16 matmul chain (y <- y @ W) at several sizes — the MXU roofline.
- ResNet-dominant 3x3 convs at the real per-stage shapes — conv roofline.
- f32 elementwise triad (y <- a*x + y) — HBM bandwidth roofline.

Output: per-kernel sustained TFLOP/s (or GB/s) + the sweep max, printed as a
table and one JSON line. The sweep max IS the measured ceiling: MFU-at-
ceiling = step_flops / (step_time * ceiling) tells whether the training step
leaves real headroom on the table or the device/tunnel is the limit.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

# Persistent compilation cache: the probes are re-run per-kernel from fresh
# processes (the tunnel makes compiles 20-50s); caching makes iteration sane.
_CACHE = os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax-cache")
try:
    jax.config.update("jax_compilation_cache_dir", _CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

# This backend shows a fixed ~1.7 ms cost PER SCAN ITERATION (measured:
# a 2048^3 matmul iter and a 66-GFLOP conv iter both floor near it, while
# an 8192^3 iter runs 8.2 ms). Chaining CHAIN ops inside each scan body
# amortizes that floor out of the kernel-rate measurement.
CHAIN = int(os.environ.get("CEILING_CHAIN", "8"))


def _timed(fn, args, iters: int) -> float:
    """Seconds per iteration: compile+warm once, then time one scanned run
    with a host fetch as the barrier. All arrays are passed as ARGUMENTS:
    a closure-captured device array is serialized into the remote-compile
    request on this backend (HTTP 413 past ~256 MiB — the root cause of the
    round-1 "batch-512 hang": batch-512 images captured by the bench step
    were a 308 MiB compile payload)."""
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: float(jnp.sum(x.astype(jnp.float32))), out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: float(jnp.sum(x.astype(jnp.float32))), out)
    return (time.perf_counter() - t0) / iters


def matmul_sustained(n: int, iters: int = 20) -> Dict[str, Any]:
    """bf16 y <- y @ W chained n×n matmul; sustained TFLOP/s."""
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (n, n), jnp.bfloat16) * (1.0 / n) ** 0.5
    y0 = jax.random.normal(key, (n, n), jnp.bfloat16)

    @jax.jit
    def run(y, w):
        def body(y, _):
            # scaled init keeps values finite across the chained multiplies
            for _i in range(CHAIN):
                y = y @ w
            return y, ()
        y, _ = jax.lax.scan(body, y, None, length=iters)
        return jnp.sum(y.astype(jnp.float32))

    dt = _timed(run, (y0, w), iters * CHAIN)
    flops = 2.0 * n * n * n
    return {"kernel": f"matmul_bf16_{n}", "tflops": flops / dt / 1e12, "iter_s": dt}


def conv_sustained(batch: int, hw: int, cin: int, cout: int, iters: int = 20) -> Dict[str, Any]:
    """bf16 3x3 stride-1 SAME conv at a ResNet-stage shape; sustained TFLOP/s."""
    key = jax.random.PRNGKey(0)
    x0 = jax.random.normal(key, (batch, hw, hw, cin), jnp.bfloat16)
    k = jax.random.normal(key, (3, 3, cin, cout), jnp.bfloat16) * 0.05
    # cout -> cin projection so the loop composes when cin != cout
    proj = jax.random.normal(key, (1, 1, cout, cin), jnp.bfloat16) * 0.05
    dn = jax.lax.conv_dimension_numbers(x0.shape, k.shape, ("NHWC", "HWIO", "NHWC"))
    y_shape = (batch, hw, hw, cout)
    dn_proj = jax.lax.conv_dimension_numbers(y_shape, proj.shape, ("NHWC", "HWIO", "NHWC"))

    @jax.jit
    def run(x, k, proj):
        def body(x, _):
            for _i in range(CHAIN):
                y = jax.lax.conv_general_dilated(x, k, (1, 1), "SAME", dimension_numbers=dn)
                x = jax.lax.conv_general_dilated(y, proj, (1, 1), "SAME",
                                                 dimension_numbers=dn_proj) * (1.0 / hw)
            return x, ()
        x, _ = jax.lax.scan(body, x, None, length=iters)
        return jnp.sum(x.astype(jnp.float32))

    dt = _timed(run, (x0, k, proj), iters * CHAIN)
    flops = 2.0 * batch * hw * hw * (3 * 3 * cin * cout + cout * cin)
    return {"kernel": f"conv3x3_bf16_b{batch}_{hw}x{hw}x{cin}->{cout}",
            "tflops": flops / dt / 1e12, "iter_s": dt}


def flash_seq_sustained(batch: int, seq: int, heads: int = 16, head_dim: int = 64,
                        iters: int = 8) -> Dict[str, Any]:
    """Pallas flash attention fwd+bwd at long sequence lengths — the
    long-context kernel evidence (8192 tokens held constant across the
    sweep; the quadratic score work grows with seq while the token count
    stays fixed, so rates show how the kernel scales with context)."""
    from kubeflow_tpu.ops.flash_attention import flash_attention

    key = jax.random.PRNGKey(0)
    shape = (batch, seq, heads, head_dim)
    q0 = jax.random.normal(key, shape, jnp.bfloat16) * 0.1

    def loss(q, k, v):
        return jnp.sum(jnp.abs(
            flash_attention(q, k, v, causal=True, interpret=False).astype(jnp.float32)))

    @jax.jit
    def run(q):
        def body(q, _):
            for _i in range(CHAIN):
                dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, q, q)
                q = (jnp.abs(dq) * 0.1 + (jnp.abs(dk) + jnp.abs(dv))
                     * jnp.bfloat16(1e-3)).astype(jnp.bfloat16) * 0.3
            return q, ()
        q, _ = jax.lax.scan(body, q, None, length=iters)
        return jnp.sum(q.astype(jnp.float32))

    dt = _timed(run, (q0,), iters * CHAIN)
    # causal fwd = 2 matmuls over the lower triangle ~ 2*2*b*h*L^2*d/2;
    # flash bwd recomputes scores + 4 more matmuls ~ 2.5x fwd
    fwd = 2.0 * b_h_l2_d(batch, heads, seq, head_dim)
    flops = 3.5 * fwd
    return {"kernel": f"flash_attn_fwd_bwd_b{batch}_L{seq}",
            "tflops": flops / dt / 1e12, "iter_s": dt}


def b_h_l2_d(b: int, h: int, l: int, d: int) -> float:
    return b * h * float(l) * l * d  # one causal-triangle matmul's MACs*2/2


def hbm_triad(mib: int = 512, iters: int = 20) -> Dict[str, Any]:
    """f32 y <- |y|*0.9999 + x : 2 reads + 1 write per element -> GB/s.
    abs() makes each chain step non-linear so XLA cannot algebraically
    collapse the chain into one op (a plain a*y+x chain measured 1.9 TB/s
    on an 0.8 TB/s part — the compiler had folded it)."""
    n = mib * 1024 * 1024 // 4
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n,), jnp.float32)
    y0 = jax.random.normal(key, (n,), jnp.float32)

    @jax.jit
    def run(y, x):
        def body(y, _):
            for _i in range(CHAIN):
                y = jnp.abs(y) * jnp.float32(0.9999) + x
            return y, ()
        y, _ = jax.lax.scan(body, y, None, length=iters)
        return jnp.sum(y)

    # XLA fuses the whole chain into one elementwise kernel, so the real
    # HBM traffic per scan ITERATION is 3 array passes (y in, x in, y out)
    # no matter how long the chain is — count exactly that.
    dt = _timed(run, (y0, x), iters)
    gbytes = 3.0 * n * 4 / 1e9
    return {"kernel": f"hbm_triad_f32_{mib}MiB", "gbs": gbytes / dt, "iter_s": dt}


def sweep() -> Dict[str, Any]:
    results: List[Dict[str, Any]] = []
    for n in (2048, 4096, 8192):
        results.append(matmul_sustained(n))
    # ResNet-50's conv budget by stage (batch matches the bench)
    for shape in ((256, 56, 64, 64), (256, 28, 128, 128), (256, 14, 256, 256)):
        results.append(conv_sustained(*shape))
    bw = hbm_triad()
    ceiling = max(r["tflops"] for r in results)
    return {"kernels": results, "hbm": bw, "ceiling_tflops": ceiling}


def flash_sweep() -> List[Dict[str, Any]]:
    """Long-context flash rows (8192 tokens held constant) —
    ``python -m e2e.ceiling --flash``; BASELINE.md round-4 table."""
    return [flash_seq_sustained(b, L)
            for b, L in ((8, 1024), (4, 2048), (2, 4096), (1, 8192))]


def main(argv: Optional[List[str]] = None) -> None:
    import sys

    from kubeflow_tpu.training.flops import detect_generation, peak_flops_per_chip

    argv = sys.argv[1:] if argv is None else argv
    gen = detect_generation()
    peak = peak_flops_per_chip(gen) / 1e12
    if "--flash" in argv:
        rows = flash_sweep()
        for r in rows:
            print(f"{r['kernel']:45s} {r['tflops']:9.1f} TF {100 * r['tflops'] / peak:7.1f}%")
        print(json.dumps({"metric": f"flash_seq_sweep_{gen}", "rows": rows}))
        return
    out = sweep()
    print(f"{'kernel':45s} {'sustained':>12s} {'of peak':>8s}")
    for r in out["kernels"]:
        print(f"{r['kernel']:45s} {r['tflops']:9.1f} TF {100 * r['tflops'] / peak:7.1f}%")
    b = out["hbm"]
    print(f"{b['kernel']:45s} {b['gbs']:9.1f} GB/s")
    print(json.dumps({
        "metric": f"kernel_ceiling_{gen}",
        "value": round(out["ceiling_tflops"], 1),
        "unit": "tflops_sustained",
        "peak_tflops": peak,
        "of_peak": round(out["ceiling_tflops"] / peak, 4),
        "hbm_gbs": round(b["gbs"], 1),
    }))


if __name__ == "__main__":
    main()
