"""Controller scalability probe — the analog of the reference's
notebook-controller loadtest (loadtest/start_notebooks.py:1-12 spawns N
Notebook CRs + PVCs and leaves observation to the operator; SURVEY.md §6
lists it as the only in-tree performance tooling).

This version measures instead of just spawning: N TPU notebooks spawn
through the full path (CR → controller → webhook → scheduler), and the
probe reports time-to-all-running, reconcile throughput, and steady-state
churn (stop/start waves). Run:  python -m e2e.loadtest [-n 50]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Any, Dict

from kubeflow_tpu.api.meta import new_object
from kubeflow_tpu.apiserver.store import Conflict
from kubeflow_tpu.controllers.notebook import STOP_ANNOTATION
from kubeflow_tpu.runtime.metrics import METRICS

from .cluster import E2ECluster, wait_for_condition
from .retry import run_with_retry

NOTEBOOK_API = "kubeflow.org/v1beta1"


def mknotebook(i: int, ns: str) -> Dict[str, Any]:
    return new_object(
        NOTEBOOK_API,
        "Notebook",
        f"load-{i}",
        ns,
        spec={"template": {"spec": {"containers": [{"name": "nb", "image": "jupyter-jax"}]}}},
    )


def ready_statefulsets(cluster, ns: str) -> int:
    """StatefulSets with >= 1 ready replica (the churn-wave convergence
    metric; shared with the race tier's churn regression test)."""
    sts = cluster.client.list("apps/v1", "StatefulSet", ns)
    return sum(1 for s in sts if (s.get("status") or {}).get("readyReplicas", 0) >= 1)


def annotate_stop(cluster, ns: str, i: int, stop: bool) -> None:
    """get->modify->update with Conflict retry: the controller's status
    writes bump resourceVersion concurrently (optimistic-concurrency loop,
    same shape as client-go's RetryOnConflict)."""

    def attempt() -> None:
        nb = cluster.client.get(NOTEBOOK_API, "Notebook", f"load-{i}", ns)
        anns = nb["metadata"].setdefault("annotations", {})
        if stop:
            anns[STOP_ANNOTATION] = "now"
        else:
            anns.pop(STOP_ANNOTATION, None)
        cluster.client.update(nb)

    run_with_retry(attempt, retries=10, delay=0.02, retry_on=(Conflict,))


def run_loadtest(n: int = 50, timeout: float = 120.0) -> Dict[str, Any]:
    # Single-host notebooks (no TPU block): the probe stresses the reconcile
    # plane, not the fake scheduler's capacity math.
    with E2ECluster(nodes=[]) as cluster:
        ns = cluster.create_profile("load@example.com", "loadtest")
        reconciles_before = METRICS.total("controller_reconcile_total")

        def running_count() -> int:
            return ready_statefulsets(cluster, ns)

        def annotate(i: int, stop: bool) -> None:
            annotate_stop(cluster, ns, i, stop)

        t0 = time.perf_counter()
        for i in range(n):
            cluster.client.create(mknotebook(i, ns))
        t_created = time.perf_counter() - t0

        wait_for_condition(
            lambda: running_count() == n, timeout, desc=f"{n} notebooks running"
        )
        t_all_running = time.perf_counter() - t0

        # Stop/start wave: every notebook scales 1→0→1 (culling churn shape).
        t1 = time.perf_counter()
        for i in range(n):
            annotate(i, stop=True)
        wait_for_condition(lambda: running_count() == 0, timeout, desc="all stopped")
        for i in range(n):
            annotate(i, stop=False)
        wait_for_condition(lambda: running_count() == n, timeout, desc="all restarted")
        t_churn = time.perf_counter() - t1

        # Delta against the pre-run snapshot: METRICS is process-global and
        # may carry counts from earlier work in the same process.
        reconciles = METRICS.total("controller_reconcile_total") - reconciles_before
        return {
            "notebooks": n,
            "create_seconds": round(t_created, 3),
            "all_running_seconds": round(t_all_running, 3),
            "stop_start_wave_seconds": round(t_churn, 3),
            "notebooks_per_second": round(n / t_all_running, 1),
            "reconciles_total": int(reconciles),
        }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("-n", type=int, default=50)
    parser.add_argument("--timeout", type=float, default=120.0)
    args = parser.parse_args(argv)
    print(json.dumps(run_loadtest(args.n, args.timeout)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
