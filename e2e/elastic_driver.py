"""Elastic-training e2e: train under repeated preemption and prove survival.

The full elastic stack (docs/ELASTICITY.md) against a real in-process
control plane on the 8-virtual-device dryrun topology (two v5e 2x4 hosts +
one spare 2x2), CI job elastic-e2e:

1. an ElasticTrainer runs the composed-4D GPT on a full 8-chip slice
   (pp=4, V=1) as a drain-graced ``trial``-priority gang;
2. preemption 1 is ORGANIC: a higher-priority ``notebook`` gang lands and
   the scheduler runs the two-phase drain protocol — the PreemptionHandler
   sees the deadline annotation between steps, urgent-checkpoints, acks,
   and the gang is evicted;
3. the trainer re-requests a slice, finds only the spare host free, and
   RESHARDS: the canonical per-layer checkpoint restores onto a 4-chip
   (pp=2, V=2) mesh;
4. preemptions 2-3 come from the chaos harness (``preempt_gang``), with the
   aggressor released so the trainer reshards back up to 8 chips; a seeded
   benign-chaos schedule (watch drops, apiserver brown-outs) runs the
   whole time;
5. a kill-9-mid-save scenario asserts the checkpoint store skips torn and
   corrupt checkpoints and resumes from the previous complete one.

Asserts: >= 3 preemptions survived, >= 1 reshard, zero steps lost beyond
the last checkpoint (each incarnation resumes at endStep+1), the elastic
loss curve matches an uninterrupted reference run within 1e-3, bounded
restart latency, and the ``training_preemptions_survived_total`` /
``training_restart_seconds`` / ``checkpoint_save_seconds`` series.

CPU-only; jit compiles of the composite step dominate the ~minutes runtime.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    )

import json
import shutil
import tempfile
import time
from typing import Optional

import numpy as np

from e2e.junit import run_driver

NAMESPACE = "default"
TOTAL_STEPS = 30
CKPT_EVERY = 5
GRACE_SECONDS = 20.0
STEP_SLEEP = 0.03  # keeps steps slower than scheduler cycles, so drains
#                    land mid-run instead of after training finishes
CHAOS_SEED = 20260805
LOSS_TOL = 1e-3

#: preferred → degraded slice shapes the provider walks on every restart
SHAPES = (
    {"pods": 2, "chips": 4, "pp": 4, "virtual": 1},  # full: both 2x4 hosts
    {"pods": 1, "chips": 4, "pp": 2, "virtual": 2},  # degraded: the spare
)


def _poll(fn, timeout: float = 30.0, interval: float = 0.05, desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def _gang_pod(name, gang, size, chips, priority_class, grace=None):
    from kubeflow_tpu.api.meta import new_object
    from kubeflow_tpu.scheduler.gang import (
        DRAIN_GRACE_ANNOTATION,
        POD_GROUP_LABEL,
        POD_GROUP_SIZE_ANNOTATION,
    )
    from kubeflow_tpu.tpu.topology import RESOURCE_TPU

    annotations = {POD_GROUP_SIZE_ANNOTATION: str(size)}
    if grace is not None:
        annotations[DRAIN_GRACE_ANNOTATION] = str(grace)
    return new_object(
        "v1", "Pod", name, NAMESPACE,
        labels={POD_GROUP_LABEL: gang},
        annotations=annotations,
        spec={
            "priorityClassName": priority_class,
            "containers": [{
                "name": "trainer",
                "resources": {"limits": {RESOURCE_TPU: str(chips)}},
            }],
        },
    )


class SliceRequester:
    """The trainer's gang-acquisition loop: ask the real scheduler for the
    preferred slice shape, accept a degraded one if the cluster can't place
    it (that's the reshard), give up on none."""

    def __init__(self, client, devices):
        self._client = client
        self._devices = list(devices)
        self.gen = 0  # bumped per granted slice; triggers key off it
        self.current_gang: Optional[str] = None

    def __call__(self, attempt: int):
        from kubeflow_tpu.training.elastic import SliceOffer

        self.gen += 1
        for shape in SHAPES:
            gang = f"train-g{self.gen}-{shape['pods']}p"
            names = [f"{gang}-{i}" for i in range(shape["pods"])]
            for n in names:
                self._client.create(_gang_pod(
                    n, gang, shape["pods"], shape["chips"], "trial",
                    grace=GRACE_SECONDS))
            if self._all_running(names, timeout=4.0):
                self.current_gang = gang
                return SliceOffer(
                    devices=self._devices[: shape["pods"] * shape["chips"]],
                    pp=shape["pp"], virtual_stages=shape["virtual"],
                    pods=names, namespace=NAMESPACE,
                )
            # shape unplaceable right now: withdraw and try the next one
            for n in names:
                self._client.delete_opt("v1", "Pod", n, NAMESPACE)
            _poll(lambda: all(
                self._client.get_opt("v1", "Pod", n, NAMESPACE) is None
                for n in names), desc="withdrawn gang gone")
        raise AssertionError("no slice shape was placeable")

    def _all_running(self, names, timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            pods = [self._client.get_opt("v1", "Pod", n, NAMESPACE) for n in names]
            if all(p is not None and (p.get("status") or {}).get("phase") == "Running"
                   for p in pods):
                return True
            time.sleep(0.05)
        return False


def run(args) -> dict:
    import jax

    from kubeflow_tpu.controllers.builtin import PodletReconciler, make_tpu_node
    from kubeflow_tpu.parallel.composite import CompositeConfig
    from kubeflow_tpu.runtime.chaos import ChaosMonkey, ChaosSchedule, Fault
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.scheduler import SchedulerReconciler
    from kubeflow_tpu.training.checkpoint import Checkpointer
    from kubeflow_tpu.training.elastic import (
        CompositeWorkload,
        ElasticTrainer,
        PreemptionHandler,
        SliceOffer,
    )

    devices = jax.devices()
    assert len(devices) == 8, f"driver needs 8 virtual devices, got {len(devices)}"
    cfg = CompositeConfig(n_layers=8, vocab_size=64)  # 8 layers: pp*V=4 both ways

    mgr = Manager()
    mgr.add(SchedulerReconciler(
        assembly_timeout=5.0, reservation_ttl=5.0,
        backoff_base=0.05, backoff_cap=0.4))
    mgr.add(PodletReconciler())
    client = mgr.client
    client.create(make_tpu_node("tpu-node-0", "v5e", "2x4", 4))
    client.create(make_tpu_node("tpu-node-1", "v5e", "2x4", 4))
    client.create(make_tpu_node("tpu-spare", "v5e", "2x2", 4))
    mgr.start()

    ckpt_dir = tempfile.mkdtemp(prefix="elastic-e2e-")
    requester = SliceRequester(client, devices)
    monkey = ChaosMonkey(client, ChaosSchedule([]), store=mgr.store)

    # -- phase triggers, keyed on (incarnation, local step) -------------------
    # gen 1 / local step 2: a higher-priority gang arrives → ORGANIC drain
    # gen 2 / local step 2: aggressor done + chaos preemption → reshard UP
    # gen 3 / local step 2: chaos preemption again → third survival
    aggressor = [f"aggr-{i}" for i in range(2)]

    def spawn_aggressor():
        for n in aggressor:
            client.create(_gang_pod(n, "aggr", 2, 4, "notebook"))

    def release_aggressor_and_preempt():
        for n in aggressor:
            client.delete_opt("v1", "Pod", n, NAMESPACE)
        monkey.inject(Fault(
            0.0, "preempt_gang", f"{NAMESPACE}/{requester.current_gang}",
            param=GRACE_SECONDS))

    def chaos_preempt():
        monkey.inject(Fault(
            0.0, "preempt_gang", f"{NAMESPACE}/{requester.current_gang}",
            param=GRACE_SECONDS))

    triggers = {(1, 2): spawn_aggressor,
                (2, 2): release_aggressor_and_preempt,
                (3, 2): chaos_preempt}

    class DrivenWorkload(CompositeWorkload):
        def __init__(self, **kw):
            super().__init__(**kw)
            self._gen = None
            self._local = 0

        def run_step(self, state, step):
            state, loss = super().run_step(state, step)
            if requester.gen != self._gen:
                self._gen, self._local = requester.gen, 0
            fire = triggers.pop((self._gen, self._local), None)
            if fire is not None:
                fire()
            self._local += 1
            time.sleep(STEP_SLEEP)
            return state, loss

    workload = DrivenWorkload(cfg=cfg, num_micro=4, microbatch=4)
    trainer = ElasticTrainer(
        workload,
        Checkpointer(ckpt_dir, max_to_keep=3),
        requester,
        TOTAL_STEPS,
        checkpoint_every=CKPT_EVERY,
        handler_factory=lambda offer: PreemptionHandler(
            client, NAMESPACE, offer.pods, poll_interval=0.02),
    )

    # benign chaos runs throughout: watch drops + apiserver brown-outs from a
    # seeded (reproducible) schedule; the Pod informer is started eagerly so
    # the watch-drop fault has a stream to sever
    mgr.cache.informer_for("v1", "Pod")
    benign = ChaosMonkey(
        client,
        ChaosSchedule.seeded(
            CHAOS_SEED, 4, 20.0,
            targets={"drop_informer_watch": ["Pod"], "delay_apiserver": [""]},
            param={"delay_apiserver": 0.2},
        ),
        store=mgr.store,
        informers=list(mgr.cache._informers.values()),
    ).start()

    try:
        t0 = time.perf_counter()
        report = trainer.run()
        elapsed = time.perf_counter() - t0
    finally:
        benign.stop()
        monkey.stop()
        mgr.stop()

    try:
        # -- survival -------------------------------------------------------
        assert report.completed, f"training never finished: {report.incarnations}"
        assert report.preemptions_survived >= 3, report.incarnations
        assert not triggers, f"untriggered phases left: {sorted(triggers)}"

        # -- at least one reshard -------------------------------------------
        shapes = [(i["offer"]["pp"], i["offer"]["virtualStages"])
                  for i in report.incarnations]
        assert len(set(shapes)) >= 2, f"no reshard happened: {shapes}"
        assert (2, 2) in shapes, f"degraded (pp=2, V=2) slice never used: {shapes}"

        # -- zero lost steps beyond the last checkpoint ---------------------
        for prev, cur in zip(report.incarnations, report.incarnations[1:]):
            assert prev["outcome"] == "preempted", prev
            assert cur["startStep"] == prev["endStep"] + 1, (prev, cur)

        # -- loss continuity vs an uninterrupted run ------------------------
        ref_workload = CompositeWorkload(cfg=cfg, num_micro=4, microbatch=4)
        state = ref_workload.init(SliceOffer(devices=devices, pp=4))
        ref = {}
        for s in range(TOTAL_STEPS):
            state, loss = ref_workload.run_step(state, s)
            ref[s] = loss
        assert set(report.losses) == set(ref), "missing steps in elastic run"
        worst = max(abs(report.losses[s] - ref[s]) for s in ref)
        assert worst <= LOSS_TOL, f"loss curve diverged: max|Δ|={worst:.2e}"

        # -- bounded restart latency ----------------------------------------
        restarts = METRICS.histogram("training_restart_seconds")
        assert restarts.total == report.restarts >= 3
        assert restarts.sum / restarts.total < 120.0, restarts.sum

        # -- telemetry ------------------------------------------------------
        assert METRICS.total("training_preemptions_survived_total") >= 3
        assert METRICS.histogram("checkpoint_save_seconds").total >= 3
        assert METRICS.total("scheduler_drains_requested_total") >= 1
        assert METRICS.value("scheduler_drains_completed_total", outcome="acked") >= 1
        assert METRICS.value("chaos_faults_injected_total", kind="preempt_gang") >= 2

        # -- kill -9 mid-save: resume from the previous complete checkpoint --
        kill9_report = kill9_scenario()

        summary = {
            "ok": True,
            "elapsed_seconds": round(elapsed, 1),
            "preemptions_survived": report.preemptions_survived,
            "restarts": report.restarts,
            "incarnations": [
                {k: v for k, v in i.items() if k != "offer"} | {"shape": s}
                for i, s in zip(report.incarnations, shapes)
            ],
            "max_loss_delta": float(worst),
            "kill9": kill9_report,
        }
        print(json.dumps(summary))
        return summary
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def kill9_scenario() -> dict:
    """A writer killed -9 mid-save leaves a torn temp dir; a bit-flipped
    leaf leaves a complete-looking but corrupt step. A restart must skip
    both and resume from the newest COMPLETE checkpoint."""
    from kubeflow_tpu.training.checkpoint import Checkpointer

    d = tempfile.mkdtemp(prefix="elastic-kill9-")
    try:
        ckpt = Checkpointer(d)
        ckpt.save(0, {"x": np.full(8, 10.0)}, meta={"step": 0})
        ckpt.save(1, {"x": np.full(8, 11.0)}, meta={"step": 1})
        # kill -9 during save(2): the temp dir never got renamed
        torn = os.path.join(d, "_tmp.2.deadbeef")
        os.makedirs(torn)
        with open(os.path.join(torn, "leaf_00000.npy"), "wb") as f:
            f.write(b"partial write")
        # silent media corruption of the newest complete step
        leaf = os.path.join(d, "step_1", "leaf_00000.npy")
        with open(leaf, "r+b") as f:
            f.seek(-1, os.SEEK_END)
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))

        restarted = Checkpointer(d)  # the post-crash process
        assert not os.path.exists(torn), "torn temp dir not reclaimed"
        tree, meta = restarted.restore_numpy()
        assert meta["step"] == 0, f"did not fall back past corrupt step: {meta}"
        np.testing.assert_array_equal(tree["x"], np.full(8, 10.0))
        return {"resumed_step": meta["step"], "skipped": [1], "torn_cleaned": True}
    finally:
        shutil.rmtree(d, ignore_errors=True)


def main(argv=None) -> int:
    return run_driver(
        suite_name="elastic-e2e",
        class_name="ElasticChaosDryrun",
        case_name=f"survive-3-preemptions-{TOTAL_STEPS}-steps",
        make_case=lambda args: lambda: run(args),
        argv=argv,
    )


if __name__ == "__main__":
    raise SystemExit(main())
