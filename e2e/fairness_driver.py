"""API priority-and-fairness e2e: the control plane under tenant abuse
(CI job apf-e2e).

Boots Store + a fairness-gated apiserver App on a real listener. The gang
scheduler + podlet run through a :class:`RemoteStore` tagged
``system:scheduler`` — every reconcile verb crosses the HTTP boundary and
the flow-control gate, exactly like a split deployment. Then:

1. QUIET BASELINE — a seeded gang wave binds with no abuse; its bind-
   latency p99 is captured from the phase delta of
   ``scheduler_bind_latency_seconds``.
2. ABUSE — a seeded abusive tenant floods the apiserver through the real
   HTTP path: a ``bulk:abuser`` chaos flood (``flood_apiserver``) plus an
   ``interactive:noisy`` LoadGenerator watch storm + churn, while a second
   gang wave is submitted. Asserts:
   - every gang still binds,
   - the low-priority flood sheds (429 + Retry-After observed by the
     flooder; nonzero ``apiserver_flowcontrol_rejected_total`` for the
     ``low`` level), while the scheduler flow is NEVER rejected,
   - bind p99 under abuse stays within ``ABUSE_P99_FACTOR``× the quiet
     baseline measured in the same run.
3. WATCH CACHE — a watch-only storm (no client LISTs) must be served from
   the apiserver's watch cache: ``apiserver_store_list_total`` stays flat.
4. COMPACTION — against a small-ring store, an informer severed mid-churn
   gets 410 Gone and recovers via the paginated relist with no missed
   events (``informer_relists_total`` moves, mirror converges).
5. CONTROL — the same flood against a fairness-DISABLED apiserver sheds
   nothing (zero 429s): the run demonstrates the protection is load-
   bearing, not vacuous.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request

SEED = 13
FAIRNESS_NODES = int(os.environ.get("FAIRNESS_NODES", "200"))
WAVE_GANGS = int(os.environ.get("FAIRNESS_GANGS", "4"))
#: abuse intensity scales with the machine — fairness shares out apiserver
#: concurrency, not CPU cycles, so a flood hot enough to saturate a
#: single-core CI worker's GIL would starve the scheduler below the
#: admission layer and measure the box, not the gate
_CORES = os.cpu_count() or 1
FLOOD_QPS = float(os.environ.get("FAIRNESS_FLOOD_QPS", str(60 * min(_CORES, 8))))
FLOOD_S = float(os.environ.get("FAIRNESS_FLOOD_S", "6"))
STORM_STREAMS = int(os.environ.get("FAIRNESS_STORM_STREAMS", str(2 * min(_CORES, 4))))
STORM_RELISTS = int(os.environ.get("FAIRNESS_STORM_RELISTS", str(8 * min(_CORES, 8))))
#: abuse-phase bind p99 must stay within this factor of the quiet baseline
ABUSE_P99_FACTOR = 2.0
#: sub-resolution baselines would make the factor check meaningless noise
P99_FLOOR_S = 0.25
#: creationTimestamp (the bind SLI's start mark) has 1 s resolution: any
#: cross-phase comparison carries that much measurement noise
TIMESTAMP_RESOLUTION_S = 1.0


def _metric_value(text: str, name: str, **labels) -> float:
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def _phase_p99(before, after) -> float:
    """p99 of the bind-latency observations BETWEEN two histogram_counts
    snapshots (None-safe: no observations yet -> zero counts)."""
    from kubeflow_tpu.runtime.metrics import quantile_from_counts

    if after is None:
        return 0.0
    buckets, counts_a, total_a = after
    counts_b, total_b = ([0] * len(counts_a), 0) if before is None else (
        list(before[1]), before[2])
    delta = [a - b for a, b in zip(counts_a, counts_b)]
    q = quantile_from_counts(buckets, delta, total_a - total_b, 0.99)
    return 0.0 if q is None else q


def run() -> dict:
    from kubeflow_tpu.apiserver.backend import DictBackend
    from kubeflow_tpu.apiserver.client import Client
    from kubeflow_tpu.apiserver.fairness import (
        LEVEL_LOW,
        DEFAULT_LEVELS,
        FlowController,
        LevelConfig,
    )
    from kubeflow_tpu.apiserver.remote import RemoteStore
    from kubeflow_tpu.apiserver.server import make_apiserver_app
    from kubeflow_tpu.apiserver.store import Store
    from kubeflow_tpu.controllers.builtin import PodletReconciler
    from kubeflow_tpu.runtime.chaos import ChaosMonkey, ChaosSchedule
    from kubeflow_tpu.runtime.informer import SharedInformer
    from kubeflow_tpu.runtime.manager import Manager
    from kubeflow_tpu.runtime.metrics import METRICS
    from kubeflow_tpu.scale.loadgen import LoadGenerator
    from kubeflow_tpu.scale.topology import synth_gangs, synthesize
    from kubeflow_tpu.scheduler import SchedulerReconciler

    topo = synthesize(FAIRNESS_NODES, seed=SEED)
    store = Store()
    # system/normal keep the production shares; low is pinned to a sliver
    # (1 seat, short queues) so a realistic flood demonstrably overflows —
    # an in-process LIST is so fast that the default 4-seat low level would
    # absorb hundreds of qps without ever queueing
    levels = tuple(c for c in DEFAULT_LEVELS if c.name != LEVEL_LOW) + (
        LevelConfig(LEVEL_LOW, seats=1, queues=4, queue_length=2, hand_size=1),)
    app = make_apiserver_app(store, fairness=FlowController(levels=levels))
    httpd = app.serve(0)
    base = f"http://127.0.0.1:{httpd.port}"

    # the control plane itself crosses the fairness gate: scheduler traffic
    # is the system flow whose starvation the gate exists to prevent
    remote = RemoteStore(base, flow="system:scheduler")
    mgr = Manager(remote)
    mgr.add(SchedulerReconciler(assembly_timeout=10.0, reservation_ttl=5.0,
                                backoff_base=0.05, backoff_cap=0.5))
    mgr.add(PodletReconciler())
    mgr.start()
    monkey = ChaosMonkey(Client(store), ChaosSchedule([]), apiserver_url=base)
    try:
        gen = LoadGenerator(base, topo, seed=SEED, flow="tenant-train")
        assert gen.register_nodes() == topo.total_nodes

        # -- phase 0: warmup — informer sync + first-reconcile costs must
        # not pollute the quiet baseline the abuse phase is judged against
        warm = synth_gangs(topo, 1, seed=SEED - 1, prefix="warm", max_size=2)
        gen.gang_wave(warm)
        gen.wait_gangs_bound([s.name for s in warm], timeout_s=90.0)

        # -- phase 1: quiet baseline -----------------------------------------
        snap0 = METRICS.histogram_counts("scheduler_bind_latency_seconds")
        wave1 = synth_gangs(topo, WAVE_GANGS, seed=SEED, prefix="quiet", max_size=4)
        gen.gang_wave(wave1)
        gen.wait_gangs_bound([s.name for s in wave1], timeout_s=90.0)
        snap1 = METRICS.histogram_counts("scheduler_bind_latency_seconds")
        p99_quiet = _phase_p99(snap0, snap1)

        # -- phase 2: abuse --------------------------------------------------
        abuser = LoadGenerator(base, topo, seed=SEED + 1, timeout_s=5.0,
                               flow="interactive:noisy")
        storm_out: dict = {}

        def storm():
            try:
                storm_out.update(abuser.watch_storm(
                    streams=STORM_STREAMS, relists=STORM_RELISTS,
                    duration_s=FLOOD_S))
            except Exception as e:  # shed requests surface here — tolerated
                storm_out["error"] = str(e)

        storm_t = threading.Thread(target=storm, daemon=True)
        storm_t.start()
        monkey.flood_apiserver("bulk:abuser", qps=FLOOD_QPS, duration_s=FLOOD_S)
        time.sleep(0.2)  # let the flood ramp before the wave lands
        wave2 = synth_gangs(topo, WAVE_GANGS, seed=SEED + 2, prefix="abuse", max_size=4)
        gen.gang_wave(wave2)
        gen.wait_gangs_bound([s.name for s in wave2], timeout_s=120.0)
        snap2 = METRICS.histogram_counts("scheduler_bind_latency_seconds")
        p99_abuse = _phase_p99(snap1, snap2)
        monkey.join(timeout=FLOOD_S + 15.0)
        storm_t.join(timeout=FLOOD_S + 15.0)
        flood = monkey.flood_stats[0]

        with urllib.request.urlopen(f"{base}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        rejected_low = _metric_value(
            text, "apiserver_flowcontrol_rejected_total", priority_level="low")
        rejected_sched = _metric_value(
            text, "apiserver_flowcontrol_rejected_total", flow="system:scheduler")
        dispatched_low = _metric_value(
            text, "apiserver_flowcontrol_dispatched_total", priority_level="low")
        assert flood["sent"] > 0, flood
        assert flood["rejected"] > 0, \
            f"the flood must be shed with 429s: {flood}"
        assert rejected_low > 0, "low-priority rejections must be counted"
        assert rejected_sched == 0, \
            f"the scheduler flow must NEVER be rejected ({rejected_sched})"
        rejected_fraction = flood["rejected"] / flood["sent"]
        bound = (max(p99_quiet, P99_FLOOR_S) * ABUSE_P99_FACTOR
                 + TIMESTAMP_RESOLUTION_S)
        assert p99_abuse <= bound, \
            (f"bind p99 under abuse {p99_abuse:.3f}s exceeds "
             f"{ABUSE_P99_FACTOR}x quiet baseline {p99_quiet:.3f}s "
             f"(+{TIMESTAMP_RESOLUTION_S}s timestamp resolution)")

        # -- phase 3: watch storms ride the watch cache ----------------------
        lists_before = METRICS.value("apiserver_store_list_total", resource="pods")
        stop = threading.Event()

        def watch_only():
            req = urllib.request.Request(
                f"{base}/api/v1/namespaces/default/pods?watch=true&sendInitial=true",
                headers={"x-flow-client": "interactive:noisy"})
            try:
                with urllib.request.urlopen(req, timeout=10.0) as resp:
                    while not stop.is_set():
                        if not resp.readline():
                            break
            except OSError:
                pass

        watchers = [threading.Thread(target=watch_only, daemon=True)
                    for _ in range(8)]
        for t in watchers:
            t.start()
        time.sleep(1.0)
        stop.set()
        gen._get("/api/v1/namespaces/default/pods")  # control: lists DO count
        lists_after = METRICS.value("apiserver_store_list_total", resource="pods")
        watch_cache_hit = (lists_after - lists_before) == 1
        assert watch_cache_hit, \
            (f"watch-only storm must not touch the store list path "
             f"(list_total moved {lists_before} -> {lists_after})")

        # -- phase 4: compaction -> 410 -> paginated relist ------------------
        small = Store(DictBackend(), watch_cache_size=4)
        iclient = Client(small)
        iclient.create({"apiVersion": "v1", "kind": "Pod",
                        "metadata": {"name": "seed-0", "namespace": "default"},
                        "spec": {}})
        relists0 = METRICS.value("informer_relists_total", kind="Pod")
        inf = SharedInformer(iclient, "v1", "Pod").start()
        try:
            assert inf.wait_synced()
            inf._watcher.close()
            for i in range(12):  # churn far past the 4-event ring
                iclient.create({"apiVersion": "v1", "kind": "Pod",
                                "metadata": {"name": f"churn-{i}",
                                             "namespace": "default"},
                                "spec": {}})
            iclient.delete("v1", "Pod", "seed-0", "default")
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if len(inf) == 12 and inf.get("seed-0", "default") is None:
                    break
                time.sleep(0.05)
            assert len(inf) == 12 and inf.get("seed-0", "default") is None, \
                f"informer did not converge after compaction: {len(inf)}"
            relists = METRICS.value("informer_relists_total", kind="Pod") - relists0
            assert relists >= 1, "recovery must go through the relist path"
        finally:
            inf.stop()

        # -- phase 5: control — no fairness, no shedding ---------------------
        open_store = Store()
        open_httpd = make_apiserver_app(open_store).serve(0)
        open_monkey = ChaosMonkey(Client(open_store), ChaosSchedule([]),
                                  apiserver_url=f"http://127.0.0.1:{open_httpd.port}")
        try:
            open_monkey.flood_apiserver("bulk:abuser", qps=FLOOD_QPS,
                                        duration_s=1.5, wait=True)
        finally:
            open_monkey.stop()
            open_httpd.close()
        open_flood = open_monkey.flood_stats[0]
        assert open_flood["sent"] > 0 and open_flood["rejected"] == 0, \
            (f"without fairness nothing sheds — the gate is what holds the "
             f"invariant: {open_flood}")

        return {
            "ok": True,
            "nodes": topo.total_nodes,
            "gangs_bound": len(wave1) + len(wave2),
            "bind_p99_quiet_s": round(p99_quiet, 4),
            "bind_p99_abuse_s": round(p99_abuse, 4),
            "flood": flood,
            "rejected_fraction_lowpri": round(rejected_fraction, 4),
            "rejected_low": rejected_low,
            "rejected_scheduler": rejected_sched,
            "dispatched_low": dispatched_low,
            "storm": storm_out,
            "watch_cache_hit": watch_cache_hit,
            "relists": relists,
            "unprotected_flood": open_flood,
        }
    finally:
        monkey.stop()
        mgr.stop()
        httpd.close()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
