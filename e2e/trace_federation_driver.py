"""Trace federation e2e: one gang-bind journey traced across three REAL
processes (CI job trace-federation-e2e).

The driver process plays the user edge: it sets its Tracer identity to
``loadgen``, mints a W3C traceparent, and submits a gang through
:class:`~kubeflow_tpu.scale.loadgen.LoadGenerator` against an apiserver
running as ``python -m kubeflow_tpu.apiserver`` in its own process, with
``python -m kubeflow_tpu.scheduler.core`` reconciling from a third. Then:

1. asserts the injected trace id appears VERBATIM in every bound pod's
   creation and bind traceparent annotations (the write path crossed two
   process hops and kept the context),
2. serves a tiny GPT in-process and sends one predict carrying the SAME
   traceparent, so the ``serving.request`` retire span joins the gang's
   trace — one trace id from user submit to model response,
3. federates all three span buffers with a :class:`TraceCollector`
   (apiserver + scheduler pulled over HTTP, the driver's own ring
   ingested directly) and asserts the assembled trace spans >= 3 services
   with the full journey's span names present,
4. decomposes the trace with ``critical_path()`` and checks the
   queue/cycle/bind segments reconstruct the scheduler's recorded
   ``gang.bind_latency_s`` within 10%, cross-checking the scheduler's
   /metrics histogram and its trace-id exemplar,
5. drives a 2x-budget burst of boring traces plus known serving 500s into
   a small tail-sampled collector and asserts every error trace and the
   slowest gang bind survive while the span bound holds.

Exit 0 on success, 1 with a JSON failure report otherwise. CPU-only; the
whole run is a handful of seconds on the presubmit topology.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

SEED = 14
NODES = int(os.environ.get("TRACE_NODES", "8"))
TAIL_BUDGET = int(os.environ.get("TRACE_TAIL_BUDGET", "48"))
ERROR_PREDICTS = 3


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url: str, timeout: float = 10.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _post_json(url: str, body: dict, headers: dict = None,
               timeout: float = 60.0):
    data = json.dumps(body).encode()
    hdrs = {"content-type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=data, headers=hdrs, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else None


def _metric_value(text: str, name: str, **labels) -> float:
    """Sum of series for ``name`` whose label set includes ``labels``."""
    total = 0.0
    for line in text.splitlines():
        if not line.startswith(name):
            continue
        rest = line[len(name):]
        if rest[:1] not in ("{", " "):
            continue
        if all(f'{k}="{v}"' in rest for k, v in labels.items()):
            total += float(line.rsplit(" ", 1)[1])
    return total


def _poll(fn, timeout: float = 30.0, interval: float = 0.1,
          desc: str = "condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        value = fn()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out after {timeout}s waiting for {desc}")


def run() -> dict:
    from kubeflow_tpu.apiserver.remote import RemoteStore
    from kubeflow_tpu.monitoring.scrape import Target
    from kubeflow_tpu.monitoring.traces import (
        TraceCollector, critical_path, traces_url)
    from kubeflow_tpu.runtime.obs import otlp_traces
    from kubeflow_tpu.runtime.tracing import (
        BIND_TRACEPARENT_ANNOTATION, TRACEPARENT_ANNOTATION, TRACER)
    from kubeflow_tpu.scale.loadgen import LoadGenerator
    from kubeflow_tpu.scale.topology import synth_gangs, synthesize
    from kubeflow_tpu.serving.server import ModelServer, gpt_served_model

    TRACER.service = "loadgen"  # the driver IS the client process
    api_port, ops_port = _free_port(), _free_port()
    base = f"http://127.0.0.1:{api_port}"
    ops = f"http://127.0.0.1:{ops_port}"
    procs: list = []
    closers: list = []
    try:
        # -- three processes: this driver, a real apiserver, a real scheduler
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.apiserver"],
            env={**os.environ, "API_PORT": str(api_port)}))
        RemoteStore(base).wait_ready(timeout=60.0)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "kubeflow_tpu.scheduler.core"],
            env={**os.environ, "APISERVER_URL": base,
                 "METRICS_PORT": str(ops_port)}))
        def ops_up():
            try:
                return _get(f"{ops}/healthz", timeout=2.0)
            except (urllib.error.URLError, OSError):
                return None

        _poll(ops_up, timeout=60.0, interval=0.25,
              desc="scheduler ops endpoints")

        # -- the traced journey: one minted trace id at the user edge -------
        trace_id = f"{SEED:032x}"
        tp = f"00-{trace_id}-{'00ab' * 4}-01"
        topo = synthesize(NODES, seed=SEED)
        gen = LoadGenerator(base, topo, seed=SEED, traceparent=tp)
        registered = gen.register_nodes()
        assert registered == topo.total_nodes, (registered, topo.total_nodes)
        shape = synth_gangs(topo, 1, seed=SEED, prefix="fed", max_size=4)[0]
        gen.submit_gang(shape)
        gen.wait_gangs_bound([shape.name], timeout_s=90.0)

        # (1) trace id verbatim in both pod annotations, on every member
        members = [p for p in gen._list_pods()
                   if p["metadata"]["name"].startswith(f"{shape.name}-")]
        assert len(members) == shape.size, [p["metadata"]["name"] for p in members]
        for pod in members:
            ann = pod["metadata"].get("annotations") or {}
            assert trace_id in ann.get(TRACEPARENT_ANNOTATION, ""), \
                f"creation annotation lost the trace: {ann}"
            assert trace_id in ann.get(BIND_TRACEPARENT_ANNOTATION, ""), \
                f"bind annotation lost the trace: {ann}"

        # (2) a predict under the SAME traceparent: the serving retire span
        # joins the gang's trace
        model = gpt_served_model(name="gpt", tiny=True, max_new_tokens=4,
                                 replicas=2)
        server = ModelServer()
        server.add(model)
        httpd = server.serve(0)
        closers += [httpd.close, server.close, model.close]
        predict = f"http://127.0.0.1:{httpd.port}/v1/models/gpt:predict"
        out = _post_json(predict, {"instances": [list(range(1, 9))]},
                         headers={"traceparent": tp})
        assert out and out.get("predictions"), out

        # (3) federation: pull apiserver + scheduler buffers over HTTP,
        # ingest the driver's own ring, assemble by trace id
        collector = TraceCollector(targets=[
            Target(job="apiserver", url=traces_url(f"{base}/metrics")),
            Target(job="scheduler", url=f"{ops}/debug/traces?limit=4096"),
        ])
        need = {"gang.submit", "apiserver.create", "gang.lifecycle",
                "schedule.bind", "serving.request"}

        def assembled():
            ok = collector.collect_once()
            assert all(ok.values()), f"trace pulls must succeed: {ok}"
            collector.ingest(otlp_traces(TRACER, limit=4096), job="loadgen")
            t = collector.trace(trace_id)
            if not t or not need <= {s["name"] for s in t["spans"]}:
                return None
            # gang.lifecycle only counts once the root closed with the
            # bind-latency observation attached
            roots = [s for s in t["spans"] if s["name"] == "gang.lifecycle"]
            if not any(isinstance(s.get("attributes", {}).get(
                    "gang.bind_latency_s"), (int, float)) for s in roots):
                return None
            return t

        trace = _poll(assembled, timeout=30.0, interval=0.25,
                      desc=f"federated gang-bind trace {trace_id}")
        assert len(trace["services"]) >= 3, \
            f"a gang bind crosses >=3 processes: {trace['services']}"
        retire = [s for s in trace["spans"] if s["name"] == "serving.request"]
        assert retire and retire[0]["traceId"] == trace_id
        assert any("replica" in (s.get("attributes") or {}) for s in retire), \
            "fleet serving spans must carry their replica identity"

        # (4) critical path reconstructs the bind-latency SLI within 10%
        path = critical_path(trace)
        assert path is not None, "gang trace must decompose"
        assert [s["name"] for s in path["segments"]] == ["queue", "cycle", "bind"], path
        measured = path["measuredBindLatencySeconds"]
        assert measured > 0, path
        # 10% relative, with an absolute floor covering thread-wakeup
        # jitter between spans on a loaded CI box
        tolerance = max(0.1 * measured, 0.05)
        assert path["reconstructionError"] <= tolerance, \
            f"segments {path['totalSeconds']}s vs measured {measured}s " \
            f"(error {path['reconstructionError']}s > {tolerance}s)"
        sched_metrics = _get(f"{ops}/metrics").decode()
        assert _metric_value(sched_metrics,
                             "scheduler_bind_latency_seconds_count") >= 1
        assert trace_id in sched_metrics, \
            "bind-latency histogram must expose the trace-id exemplar"
        binds = collector.slowest_binds(n=5)
        assert any(r["traceId"] == trace_id and r["bound"] for r in binds), binds

        # (5) tail sampling under burst: 2x-budget boring traces + known
        # error traces into a small-budget collector
        errors = 0
        for _ in range(ERROR_PREDICTS):
            try:
                # a zero budget expires on arrival: deterministic 504, and
                # the serving dispatch span goes ERROR
                _post_json(predict, {"instances": [list(range(1, 9))],
                                     "timeout_ms": 0})
            except urllib.error.HTTPError as err:
                assert err.code >= 500, err.code
                errors += 1
        assert errors == ERROR_PREDICTS, "expired predicts must 5xx"

        # size the budget from what must survive: every error trace seen by
        # any of the three processes, plus the gang-bind trace (slowest
        # decile). The burst then doubles it with boring one-span traces.
        api_target = Target(job="apiserver", url=traces_url(f"{base}/metrics"))
        sched_target = Target(job="scheduler",
                              url=f"{ops}/debug/traces?limit=4096")
        tail = TraceCollector(max_spans=TAIL_BUDGET)  # budget set below
        docs = [(tail.fetch(api_target), "apiserver"),
                (tail.fetch(sched_target), "scheduler"),
                (otlp_traces(TRACER, limit=4096), "loadgen")]
        by_trace: dict = {}
        for doc, _job in docs:
            for rs in doc["resourceSpans"]:
                for sc in rs["scopeSpans"]:
                    for s in sc["spans"]:
                        by_trace.setdefault(s["traceId"], {})[s["spanId"]] = s
        error_ids = {tid for tid, spans in by_trace.items()
                     if any((s.get("status") or {}).get("code") == "ERROR"
                            for s in spans.values())}
        assert error_ids, "expired predicts must produce error traces"
        protected = error_ids | {trace_id}
        budget = sum(len(by_trace.get(t, {})) for t in protected) + 16
        tail.max_spans = budget
        burst = 2 * budget
        for _ in range(burst):  # boring single-span traces
            _get(f"{base}/healthz")
        for doc, job in docs:
            tail.ingest(doc, job=job)
        tail.add_target(api_target)
        tail.add_target(sched_target)
        tail.collect_once()  # pulls the burst, then enforces the bound
        kept = set(tail.trace_ids())
        assert error_ids <= kept, \
            f"tail sampling dropped error traces: {error_ids - kept}"
        assert trace_id in kept, "slowest gang bind must survive sampling"
        kept_spans = sum(tail.trace(t)["spanCount"] for t in kept)
        assert kept_spans <= budget, (kept_spans, budget)
        assert len(kept) < len(by_trace) + burst, "sampling must drop traces"

        return {
            "ok": True,
            "traceId": trace_id,
            "services": trace["services"],
            "spanCount": trace["spanCount"],
            "criticalPath": path,
            "tail": {"kept_traces": len(kept), "kept_spans": kept_spans,
                     "error_traces": len(error_ids)},
        }
    finally:
        for close in closers:
            close()
        for proc in procs:
            proc.terminate()
        for proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def main() -> int:
    try:
        report = run()
    except AssertionError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
